"""Export a native servable as a TensorFlow SavedModel — the reverse
interop leg.

The importer (interop/savedmodel.py) brings TF-Serving artifacts IN; this
module takes trained in-tree models OUT to consumers still running
`tensorflow_model_server`: the zoo forward is converted with jax2tf
(StableHLO carried in an `XlaCallModule` op, which TF's runtime executes
natively — jax 0.9 emits this for every conversion mode), wrapped in the
reference serving contract (`feat_ids` DT_INT64 + `feat_wts` DT_FLOAT
[n,F] -> `prediction_node`, DCNClient.java:98-108), with the vocab fold
expressed in TF ops (`floormod` == the host fold's exact mod) so int64
ids beyond 2^31 survive exactly as they do in-tree. Weights land as
ordinary tf.Variables, so the artifact has the standard `variables/`
TensorBundle layout and version-directory lifecycle tools work unchanged.

This is the BASELINE.json north star's direction ("a jax2tf-exported
SavedModel") implemented as the exit path; round-trip intake of such an
artifact by OUR graph executor is out of scope by design — XlaCallModule
embeds StableHLO, not TF ops, and the native side serves its own
checkpoints (train/checkpoint.py) without any TF detour.

MUST run in a process that has NOT imported the vendored protos: our
tensorflow.* descriptors collide with TensorFlow's in the process-wide
descriptor pool. `python -m distributed_tf_serving_tpu.interop.export`
imports tensorflow first and only proto-free subpackages after (models/
train keep their proto imports lazy for exactly this reason —
models/registry.py note).
"""

from __future__ import annotations

import argparse
import json
import sys


def publish_version(
    base_dir: str,
    write_fn,
    at_least: int = 1,
    max_attempts: int = 10,
) -> tuple[int, str]:
    """Land one artifact in a TF-Serving versioned base dir ATOMICALLY,
    allocating the next monotonic version number: `<base>/<N>` where N =
    max(existing numeric dirs, at_least - 1) + 1.

    `write_fn(tmp_dir)` writes the complete artifact into a sibling temp
    directory (dot-prefixed and non-numeric, so the version watcher's
    scan never lists it); the commit is a single os.rename into the
    numbered slot. The watcher's `_version_ready` probe therefore can
    never observe a half-written version dir — the probe only fires on
    directories that exist, and a published directory exists only fully
    written. Concurrent publishers can race the SAME number: the loser's
    rename fails (the winner's landed dir is non-empty, so rename raises
    ENOTEMPTY/EEXIST rather than silently merging), the allocator
    re-scans and retries the rename under the next number — the written
    artifact is reused, never re-generated, and the directory number is
    authoritative over anything the artifact recorded (the watcher's own
    loader contract). Returns (version, path).

    TF-free; the lifecycle plane's publisher, soaks, and tests call this
    with whatever writer fits (train/checkpoint.py save_servable,
    export_servable, a test fixture). The number allocation reuses the
    watcher's OWN scanner (lazy import), so publisher and watcher can
    never disagree about what counts as a version directory."""
    import os
    import shutil

    from ..serving.version_watcher import scan_versions

    base = os.path.abspath(str(base_dir))
    os.makedirs(base, exist_ok=True)

    def _numeric_versions() -> list[int]:
        return list(scan_versions(base))

    tmp = os.path.join(base, f".tmp-publish-{os.getpid()}-{id(write_fn):x}")
    shutil.rmtree(tmp, ignore_errors=True)
    try:
        write_fn(tmp)
        if not os.path.isdir(tmp):
            raise RuntimeError(
                f"publish writer did not create the artifact dir {tmp}"
            )
        last_exc: OSError | None = None
        for _ in range(max_attempts):
            version = max(_numeric_versions() + [int(at_least) - 1]) + 1
            dst = os.path.join(base, str(version))
            try:
                os.rename(tmp, dst)
            except OSError as exc:
                # A racing publisher landed this number first: the rename
                # onto its non-empty dir raises (ENOTEMPTY/EEXIST) instead
                # of silently merging. Only a now-existing destination is
                # a collision; anything else — EXDEV, EACCES — is a real
                # failure and must surface, not spin.
                if not os.path.isdir(dst):
                    raise
                last_exc = exc
                continue
            return version, dst
        raise RuntimeError(
            f"could not allocate a version under {base} after "
            f"{max_attempts} collisions"
        ) from last_exc
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def publish_export(
    base_dir: str, checkpoint_dir: str, validate: bool = True,
    at_least: int = 1,
) -> dict:
    """export_servable -> the next numeric version slot under `base_dir`
    (the SavedModel flavor of the lifecycle publish path; requires
    TensorFlow in-process like export_servable itself). The export's own
    validate-then-commit runs inside the publish temp dir, so the rename
    into the numbered slot stays the single commit point."""
    summary: dict = {}

    def write(tmp_dir: str) -> None:
        summary.update(export_servable(checkpoint_dir, tmp_dir, validate=validate))

    version, path = publish_version(base_dir, write, at_least=at_least)
    summary.update({"version": version, "path": path})
    return summary


def export_servable(checkpoint_dir: str, out_dir: str, validate: bool = True) -> dict:
    """Convert the checkpointed servable to a SavedModel at `out_dir`.

    Returns a summary dict (model kind, num params, validation result).
    Supports the standard 2-input CTR contract and the 3-input
    dense_features (DLRM) contract; anything else raises."""
    import os

    import tensorflow as tf  # noqa: F401 — must precede any proto import
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # This image's sitecustomize pins the axon TPU platform OVER the
        # env var; honoring an explicit CPU request needs the config-level
        # override before any backend initializes (tests/conftest.py note)
        # — otherwise a wedged relay hangs the export inside backend init.
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.experimental import jax2tf

    from ..train.checkpoint import load_servable

    servable = load_servable(checkpoint_dir)
    model = servable.model
    config = model.config
    sig = servable.signature("")
    input_names = sorted(s.name for s in sig.inputs)
    dense_dim = None
    if input_names == ["dense_features", "feat_ids", "feat_wts"]:
        dense_spec = sig.input_specs["dense_features"]
        dense_dim = dense_spec.shape[1] if dense_spec.shape else None
        if not dense_dim:
            # A declared-but-unknown dense width must FAIL, not silently
            # ship a 2-input artifact: DLRM substitutes zeros for a missing
            # dense input, so validation alone could never catch the
            # dropped contract (review finding).
            raise NotImplementedError(
                "dense_features with unknown width cannot be exported "
                f"(signature shape {dense_spec.shape}); re-save the "
                "servable with a concrete num_dense_features"
            )
    elif input_names != ["feat_ids", "feat_wts"]:
        raise NotImplementedError(
            f"export supports the CTR contracts (2-input, or 3-input with "
            f"dense_features); servable declares {input_names}"
        )
    if not model.folds_ids_on_host:
        raise NotImplementedError(
            "export requires a zoo servable with the host id fold contract"
        )
    F = config.num_fields
    vocab = config.vocab_size
    params = jax.tree.map(np.asarray, servable.params)

    def forward(p, ids32, wts, dense=None):
        batch = {"feat_ids": ids32, "feat_wts": wts}
        if dense is not None:
            batch["dense_features"] = dense
        return model.apply(p, batch)["prediction_node"]

    poly = [None, f"(b, {F})", f"(b, {F})"]
    if dense_dim is not None:
        poly.append(f"(b, {dense_dim})")
    tf_fn = jax2tf.convert(forward, polymorphic_shapes=poly, with_gradient=False)

    class ExportedCTR(tf.Module):
        pass

    module = ExportedCTR()
    # tf.Variables per leaf: standard variables/ layout in the artifact.
    module.params = tf.nest.map_structure(tf.Variable, params)

    specs = [
        tf.TensorSpec([None, F], tf.int64, name="feat_ids"),
        tf.TensorSpec([None, F], tf.float32, name="feat_wts"),
    ]
    if dense_dim is not None:
        specs.append(
            tf.TensorSpec([None, dense_dim], tf.float32, name="dense_features")
        )

    @tf.function(input_signature=specs)
    def serve(feat_ids, feat_wts, dense_features=None):
        # TF-side exact fold (floormod == mathematical mod): int64 wire ids
        # stay faithful past 2^31, and the converted fn sees the folded
        # int32 ids the in-tree serving path feeds the model.
        ids32 = tf.cast(tf.math.floormod(feat_ids, tf.constant(vocab, tf.int64)), tf.int32)
        args = (ids32, feat_wts) if dense_features is None else (
            ids32, feat_wts, dense_features
        )
        return {"prediction_node": tf_fn(module.params, *args)}

    module.serve = serve
    # Validate-then-commit: the artifact is written to a sibling temp dir,
    # validated THROUGH TF from there, and only renamed into place when it
    # passes — a version watcher pointed at the output base path must
    # never see a complete-looking directory holding a diverged model
    # (same protocol as train/checkpoint.py save_servable).
    import shutil

    tmp_dir = out_dir.rstrip("/") + f".tmp-export-{os.getpid()}"
    shutil.rmtree(tmp_dir, ignore_errors=True)
    try:
        tf.saved_model.save(module, tmp_dir, signatures={"serving_default": serve})
        _write_warmup_assets(tmp_dir, servable.name, F, dense_dim)
        summary = {
            "out": out_dir,
            "model": servable.name,
            "version": servable.version,
            "num_fields": F,
            "vocab_size": vocab,
            "param_leaves": len(jax.tree.leaves(params)),
        }
        if validate:
            # Reload the artifact THROUGH TF and compare against the
            # in-tree forward on ids past 2^31 (the fold-fidelity
            # regression the importer tests pin in the other direction).
            # Scores are sigmoid outputs in (0,1): a single absolute gate
            # is the right metric, and it is the SAME bound the export
            # tests assert — one threshold, no flaky gap between them.
            max_abs_err_bound = 1e-5
            rng = np.random.RandomState(7)
            ids = rng.randint(0, 1 << 40, size=(16, F)).astype(np.int64)
            wts = rng.rand(16, F).astype(np.float32)
            feeds = {"feat_ids": tf.constant(ids), "feat_wts": tf.constant(wts)}
            extra = ()
            if dense_dim is not None:
                dense = rng.rand(16, dense_dim).astype(np.float32)
                feeds["dense_features"] = tf.constant(dense)
                extra = (dense,)
            reloaded = tf.saved_model.load(tmp_dir).signatures["serving_default"]
            got = reloaded(**feeds)["prediction_node"].numpy()
            from .. import native

            want = np.asarray(
                forward(servable.params, native.fold_ids(ids, vocab), wts, *extra)
            )
            err = float(np.max(np.abs(got - want)))
            if err >= max_abs_err_bound:
                raise RuntimeError(
                    f"exported SavedModel diverges from the native forward "
                    f"(max abs err {err:.3e} >= {max_abs_err_bound})"
                )
            summary["validated"] = True
            summary["max_abs_err"] = err
        shutil.rmtree(out_dir, ignore_errors=True)
        os.replace(tmp_dir, out_dir)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
    return summary


def _write_warmup_assets(artifact_dir: str, model_name: str, num_fields: int,
                         dense_dim: int | None) -> None:
    """Give the artifact TF-Serving's warmup convention: a representative
    predict request in assets.extra/tf_serving_warmup_requests, so
    tensorflow_model_server (and our own version watcher) compile/warm the
    serving signature at load instead of on the first real request.

    Written by a TF-FREE subprocess: the PredictionLog record needs our
    vendored tensorflow.serving bindings, which cannot share this
    process's descriptor pool with TensorFlow (module docstring).
    """
    import os
    import subprocess

    import numpy as np

    rng = np.random.RandomState(11)
    warm = {
        "feat_ids": rng.randint(0, 1 << 40, size=(16, num_fields)).astype(np.int64),
        "feat_wts": rng.rand(16, num_fields).astype(np.float32),
    }
    if dense_dim is not None:
        warm["dense_features"] = rng.rand(16, dense_dim).astype(np.float32)
    extra_dir = os.path.join(artifact_dir, "assets.extra")
    os.makedirs(extra_dir, exist_ok=True)
    npz = os.path.join(extra_dir, "_warm_inputs.npz")
    np.savez(npz, **warm)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # never let the child touch a device
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            env.get("PYTHONPATH"),
        ) if p
    )
    try:
        subprocess.run(
            [sys.executable, "-c", (
                "import sys, numpy as np\n"
                "from distributed_tf_serving_tpu.serving.warmup import (\n"
                "    make_warmup_record, write_tfrecords)\n"
                "arrays = dict(np.load(sys.argv[1]))\n"
                "write_tfrecords(sys.argv[2], [make_warmup_record(arrays, sys.argv[3])])\n"
            ), npz, os.path.join(extra_dir, "tf_serving_warmup_requests"),
             model_name],
            check=True, capture_output=True, text=True, timeout=300, env=env,
        )
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"warmup-asset writer failed: {e.stderr[-1000:]}"
        ) from e
    finally:
        os.remove(npz)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="Export a native servable checkpoint as a TF SavedModel"
    )
    parser.add_argument("--checkpoint", required=True,
                        help="servable checkpoint dir (train.save_servable)")
    parser.add_argument("--out", required=True, help="SavedModel output dir")
    parser.add_argument("--no-validate", action="store_true")
    args = parser.parse_args(argv)
    summary = export_servable(
        args.checkpoint, args.out, validate=not args.no_validate
    )
    print(json.dumps(summary))


if __name__ == "__main__":
    sys.exit(main())
