"""SavedModel importer: TF-Serving's on-disk format -> native Servable.

Split by dependency, so serving never imports TensorFlow:

1. `read_saved_model` / `signatures_from_meta_graph` — parse
   `saved_model.pb` with the vendored wire-compatible bindings
   (proto/tf_saved_model.proto); the exported SignatureDefs
   (meta_graph.proto:297-311 upstream) become the Servable's signature map,
   so GetModelMetadata answers exactly what the original export declared.
2. `extract_variables` — one-shot subprocess running TensorFlow's
   checkpoint reader over `variables/variables.*` (TensorBundle is TF's
   private format) and dumping a plain `.npz`. TF must not be imported in
   this process: both register `tensorflow.*` symbols in the default
   descriptor pool and collide.
3. `map_variables` — places the extracted arrays into a model-zoo param
   tree: explicit {param-path: variable-name} mapping when given, otherwise
   unique-shape matching with an order-based tiebreak for repeated shapes
   (MLP stacks); ambiguity fails loudly rather than guessing silently.

`import_savedmodel` composes the three into a registry-ready Servable;
the CLI (`python -m distributed_tf_serving_tpu.interop.savedmodel`)
converts a SavedModel directory into a native checkpoint
(train/checkpoint.py layout) for `--checkpoint` serving.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import re
import subprocess
import sys
import textwrap

import numpy as np

log = logging.getLogger("dts_tpu.interop")

from ..models.base import ModelConfig, build_model
from ..models.registry import Servable, Signature, TensorSpec

SERVE_TAG = "serve"
# Object-graph checkpoints suffix every value; strip for readable names.
_ATTR_SUFFIX = "/.ATTRIBUTES/VARIABLE_VALUE"


class SavedModelImportError(RuntimeError):
    pass


# --------------------------------------------------------------- metadata


def read_saved_model(saved_model_dir):
    """Parse `saved_model.pb` natively; returns the SavedModel proto."""
    from ..proto import tf_saved_model_pb2 as sm

    path = pathlib.Path(saved_model_dir) / "saved_model.pb"
    if not path.exists():
        raise SavedModelImportError(f"{path} not found (not a SavedModel dir?)")
    proto = sm.SavedModel()
    proto.ParseFromString(path.read_bytes())
    if not proto.meta_graphs:
        raise SavedModelImportError(f"{path} contains no meta graphs")
    return proto


def serve_meta_graph(saved_model):
    """The MetaGraphDef tagged `serve` (TF-Serving's loader selects by tag;
    meta_graph.proto:62-66 upstream), falling back to the only graph."""
    for mg in saved_model.meta_graphs:
        if SERVE_TAG in mg.meta_info_def.tags:
            return mg
    if len(saved_model.meta_graphs) == 1:
        return saved_model.meta_graphs[0]
    tags = [list(m.meta_info_def.tags) for m in saved_model.meta_graphs]
    raise SavedModelImportError(f"no meta graph tagged {SERVE_TAG!r}; have {tags}")


def signatures_from_meta_graph(meta_graph) -> dict[str, Signature]:
    """SignatureDef map -> native Signature map (alias keys, dtypes, shapes
    preserved; -1/unknown dims become None)."""

    def specs(infos) -> tuple[TensorSpec, ...]:
        out = []
        for alias, info in sorted(infos.items()):
            if info.tensor_shape.unknown_rank:
                dims = None  # unknown rank, not a scalar (tensor_shape.proto)
            else:
                dims = tuple(
                    None if d.size < 0 else int(d.size) for d in info.tensor_shape.dim
                )
            out.append(TensorSpec(name=alias, dtype=info.dtype, shape=dims))
        return tuple(out)

    sigs = {}
    for name, sd in meta_graph.signature_def.items():
        sigs[name] = Signature(
            inputs=specs(sd.inputs),
            outputs=specs(sd.outputs),
            method_name=sd.method_name,
        )
    if not sigs:
        raise SavedModelImportError("SavedModel declares no signatures")
    return sigs


# -------------------------------------------------------------- variables

_EXTRACT_SCRIPT = textwrap.dedent(
    """
    import sys
    import numpy as np
    import tensorflow as tf

    prefix, out = sys.argv[1], sys.argv[2]
    reader = tf.train.load_checkpoint(prefix)
    arrays = {}
    for name in reader.get_variable_to_shape_map():
        if (
            "OBJECT_GRAPH" in name
            or "/.OPTIMIZER_SLOT/" in name
            or name.split("/")[0] == "save_counter"
        ):
            continue  # bookkeeping / optimizer state, not servable weights
        arrays[name] = reader.get_tensor(name)
    np.savez(out, **arrays)
    print(f"extracted {len(arrays)} variables")
    """
)


def extract_variables(saved_model_dir, out_npz, python: str = sys.executable) -> pathlib.Path:
    """Dump the SavedModel's variables to `.npz` via a TensorFlow subprocess.

    TF is only needed here (its TensorBundle reader); the output npz is the
    cacheable, TF-free artifact everything downstream consumes.
    """
    prefix = pathlib.Path(saved_model_dir) / "variables" / "variables"
    out_npz = pathlib.Path(out_npz)
    proc = subprocess.run(
        [python, "-c", _EXTRACT_SCRIPT, str(prefix), str(out_npz)],
        capture_output=True,
        text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        raise SavedModelImportError(
            f"variable extraction failed (is tensorflow importable by {python}?):\n"
            f"{proc.stderr.strip()[-2000:]}"
        )
    return out_npz


# Graph-executor binding needs variables keyed by the serving graph's
# VarHandleOp shared_name (what ReadVariableOp resolves), not by checkpoint
# object paths; tf.saved_model.load restores variables under exactly those
# names (verified against tf 2.21 exports), so the loaded signature graph is
# the authoritative name source.
_EXTRACT_GRAPH_SCRIPT = textwrap.dedent(
    """
    import sys
    import numpy as np
    import tensorflow as tf

    src, out, sig_name = sys.argv[1], sys.argv[2], sys.argv[3]
    obj = tf.saved_model.load(src)
    f = obj.signatures[sig_name] if sig_name in obj.signatures else (
        next(iter(obj.signatures.values()))
    )
    arrays = {}
    for v in f.graph.variables:
        arrays[v.name.split(":")[0]] = v.numpy()
    if not arrays:
        # TF1-format SavedModel (simple_save / SavedModelBuilder): the v1
        # loader wrapper exposes no f.graph.variables, but its TensorBundle
        # stores values under the VariableV2 node names directly — exactly
        # the keys the graph executor binds (graph_exec.py VariableV2).
        import os
        prefix = os.path.join(src, "variables", "variables")
        reader = tf.train.load_checkpoint(prefix)
        for name in reader.get_variable_to_shape_map():
            arrays[name] = reader.get_tensor(name)
    np.savez(out, **arrays)
    print(f"extracted {len(arrays)} graph variables")
    """
)


def extract_graph_variables(
    saved_model_dir, out_npz, signature_name: str = "serving_default",
    python: str = sys.executable,
) -> pathlib.Path:
    """Dump the serving signature's variables keyed by shared_name (the
    graph-executor binding) via a TensorFlow subprocess."""
    out_npz = pathlib.Path(out_npz)
    proc = subprocess.run(
        [python, "-c", _EXTRACT_GRAPH_SCRIPT, str(saved_model_dir), str(out_npz),
         signature_name],
        capture_output=True,
        text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        raise SavedModelImportError(
            f"graph-variable extraction failed (is tensorflow importable by "
            f"{python}?):\n{proc.stderr.strip()[-2000:]}"
        )
    return out_npz


def _clean_name(name: str) -> str:
    return name[: -len(_ATTR_SUFFIX)] if name.endswith(_ATTR_SUFFIX) else name


def _is_bookkeeping(name: str) -> bool:
    """TF checkpoint bookkeeping that must never bind to model params (also
    filtered at extraction; re-checked here for pre-extracted npz files)."""
    return (
        name.split("/")[0] == "save_counter"
        or "OBJECT_GRAPH" in name
        or "/.OPTIMIZER_SLOT/" in name
    )


def _natural_key(name: str):
    """Numeric-aware sort: layer_2 before layer_10 (plain lexicographic
    ordering would shuffle same-shape stacks past 10 layers)."""
    return [int(tok) if tok.isdigit() else tok for tok in re.split(r"(\d+)", name)]


def _flatten_params(tree, prefix=()) -> dict[str, np.ndarray]:
    """Nested dict/list param tree -> {'a/b/0/w': array} paths."""
    flat = {}
    if isinstance(tree, dict):
        items = tree.items()
    elif isinstance(tree, (list, tuple)):
        items = enumerate(tree)
    else:
        return {"/".join(map(str, prefix)): tree}
    for key, sub in items:
        flat.update(_flatten_params(sub, prefix + (str(key),)))
    return flat


def _unflatten_like(template, flat: dict[str, np.ndarray], prefix=()):
    if isinstance(template, dict):
        return {k: _unflatten_like(v, flat, prefix + (str(k),)) for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        seq = [_unflatten_like(v, flat, prefix + (str(i),)) for i, v in enumerate(template)]
        return type(template)(seq) if isinstance(template, tuple) else seq
    return flat["/".join(map(str, prefix))]


# Name-pattern roles for mapping-free import of real-world exports
# (VERDICT.md round-1 item 4). Keras/estimator exports carry a standard
# vocabulary (dense_1/kernel, embedding/embeddings, linear/linear_model/...,
# tfrs cross layers); classifying both sides into coarse roles lets
# same-shape kernels from DIFFERENT groups (a cross (d,d) vs an MLP (d,d))
# bind without an explicit mapping. First match wins, so the more specific
# roles come first.
_VAR_ROLE_PATTERNS: tuple[tuple[str, str], ...] = (
    ("embedding", r"embedding|embeddings|emb_|_emb\b|lookup_table"),
    ("wide", r"wide|linear_model|(^|/)linear(/|$)"),
    ("cross", r"cross"),
    ("user", r"user|query"),
    ("item", r"(^|/|_)item|candidate"),
    ("out", r"logits|output|head|prediction|score|(^|/)out(/|$)"),
    ("deep", r"dense|dnn|deep|mlp|hidden|(^|/)fc|sequential|tower"),
)

_PARAM_ROLE_PATTERNS: tuple[tuple[str, str], ...] = (
    ("embedding", r"embedding"),
    ("wide", r"wide|linear"),
    ("cross", r"cross"),
    ("user", r"user"),
    ("item", r"item"),
    ("out", r"(^|/)out(/|$)|bias"),
    ("deep", r"mlp"),
)


def _role(name: str, patterns) -> str:
    low = name.lower()
    for role, pat in patterns:
        if re.search(pat, low):
            return role
    return "other"


def map_variables(
    variables: dict[str, np.ndarray],
    target_params,
    mapping: dict[str, str] | None = None,
):
    """Place extracted TF variables into a model-zoo param tree.

    `mapping` is {our-param-path: tf-variable-name} and wins outright
    (variable names accepted with or without the checkpoint's
    `/.ATTRIBUTES/VARIABLE_VALUE` suffix). Without it, two passes:

    1. *Role pass* — both sides are classified into coarse semantic roles by
       name patterns (_VAR_ROLE_PATTERNS: the common Keras/estimator export
       vocabulary; _PARAM_ROLE_PATTERNS: the zoo's own tree vocabulary).
       Within a (role, shape) bucket whose candidate counts agree, variables
       bind to params in natural-sorted-name vs tree order. Buckets that
       don't line up defer — the role pass never errors.
    2. *Shape pass* (the original semantics) — leftovers bind by exact
       shape; a shape held by exactly one variable and one slot binds
       directly; repeated shapes bind in natural order only within one
       indexed stack. Leftover ambiguity or mismatch raises with the full
       candidate list.
    """
    variables = {
        _clean_name(k): np.asarray(v)
        for k, v in variables.items()
        if not _is_bookkeeping(_clean_name(k))
    }
    flat_target = _flatten_params(target_params)
    chosen: dict[str, str] = {}

    if mapping:
        mapping = {p: _clean_name(v) for p, v in mapping.items()}
        missing = set(mapping) - set(flat_target)
        if missing:
            raise SavedModelImportError(f"mapping names unknown param paths: {sorted(missing)}")
        bad_vars = set(mapping.values()) - set(variables)
        if bad_vars:
            raise SavedModelImportError(
                f"mapping names unknown variables: {sorted(bad_vars)}; "
                f"available: {sorted(variables)}"
            )
        chosen.update(mapping)

    def remaining():
        used = set(chosen.values())
        params = [p for p in flat_target if p not in chosen]  # tree order
        varnames = [v for v in sorted(variables, key=_natural_key) if v not in used]
        return params, varnames

    # ---- pass 1: role-partitioned shape matching (defer on any mismatch)
    unmapped_params, unused_vars = remaining()
    buckets: dict[tuple[str, tuple], tuple[list[str], list[str]]] = {}
    for p in unmapped_params:
        key = (_role(p, _PARAM_ROLE_PATTERNS), tuple(np.shape(flat_target[p])))
        buckets.setdefault(key, ([], []))[0].append(p)
    for v in unused_vars:
        key = (_role(v, _VAR_ROLE_PATTERNS), tuple(variables[v].shape))
        if key in buckets:
            buckets[key][1].append(v)
    for (role, _shape), (params, cands) in buckets.items():
        if role == "other" or not params or len(params) != len(cands):
            continue  # defer to the shape pass
        if len(params) > 1 and len({re.sub(r"\d+", "#", p) for p in params}) > 1:
            continue  # multiple stacks share (role, shape): don't guess here
        for p, v in zip(params, cands):
            chosen[p] = v

    # ---- pass 2: global shape matching over whatever the role pass left
    unmapped_params, unused_vars = remaining()
    by_shape_vars: dict[tuple, list[str]] = {}
    for v in unused_vars:
        by_shape_vars.setdefault(tuple(variables[v].shape), []).append(v)
    by_shape_params: dict[tuple, list[str]] = {}
    for p in unmapped_params:  # tree order
        by_shape_params.setdefault(tuple(np.shape(flat_target[p])), []).append(p)

    for shape, params in by_shape_params.items():
        cands = by_shape_vars.get(shape, [])
        if len(cands) < len(params):
            raise SavedModelImportError(
                f"no variable of shape {shape} for param(s) {params}; "
                f"unused variables: { {v: variables[v].shape for v in unused_vars} }"
            )
        if len(cands) > len(params):
            raise SavedModelImportError(
                f"ambiguous shape {shape}: params {params} vs variables {cands}; "
                "pass an explicit mapping for these"
            )
        if len(params) > 1:
            # Order-based binding is only trustworthy within ONE indexed
            # stack (cross/0/w, cross/1/w, ...). Same-shape params from
            # different groups (a cross kernel and an MLP kernel both
            # (16,16)) would zip against variable names whose sort order
            # has no relation to our tree order — fail instead of guessing.
            stems = {re.sub(r"\d+", "#", p) for p in params}
            if len(stems) > 1:
                raise SavedModelImportError(
                    f"shape {shape} is shared across different param groups "
                    f"{sorted(stems)} ({params}); order-based matching would "
                    "guess — pass an explicit mapping for these"
                )
        for p, v in zip(params, cands):
            chosen[p] = v

    flat_out = {}
    for path, var_name in chosen.items():
        arr = variables[var_name]
        want = flat_target[path]
        if tuple(arr.shape) != tuple(np.shape(want)):
            raise SavedModelImportError(
                f"shape mismatch for {path}: param {np.shape(want)} vs "
                f"variable {var_name} {arr.shape}"
            )
        flat_out[path] = arr.astype(np.asarray(want).dtype, copy=False)
    return _unflatten_like(target_params, flat_out)


def infer_generic_architecture(
    variables: dict[str, np.ndarray],
    signatures: dict | None,
    config: ModelConfig,
) -> tuple[ModelConfig, dict[str, str]]:
    """Classify a non-zoo export as "embedding bag -> dense chain -> logit"
    and derive the generic family's config + an EXPLICIT variable mapping
    from the export's own shapes (VERDICT r2 item 7: the best-effort
    fallback at the import boundary). Raises SavedModelImportError with the
    structural reason when the export is not that shape — the caller folds
    it into the actionable rejection.

    Inference rules:
    - the embedding table is the 2-D variable classified `embedding` by
      name (falling back to the largest-rows 2-D variable); its shape gives
      (vocab_size, embed_dim);
    - num_fields comes from the serving_default `feat_ids` spec when the
      export declares it, else the caller's config;
    - the dense chain is recovered by shape-chaining: kernels must form one
      sequence in_0=F*D -> ... -> out_n=1 using EVERY non-embedding 2-D
      variable exactly once (depth-first over same-in-dim alternatives), so
      no weight is silently dropped; each kernel's bias binds by sibling
      name (kernel->bias) or uniquely by shape.
    """
    variables = {
        _clean_name(k): np.asarray(v)
        for k, v in variables.items()
        if not _is_bookkeeping(_clean_name(k))
    }

    num_fields = config.num_fields
    sig = (signatures or {}).get("serving_default")
    if sig is not None:
        for spec in sig.inputs:
            if spec.name == "feat_ids" and spec.shape and len(spec.shape) == 2:
                if spec.shape[1]:
                    num_fields = int(spec.shape[1])

    two_d = {k: v for k, v in variables.items() if v.ndim == 2}
    one_d = {k: v for k, v in variables.items() if v.ndim == 1}
    other = {k: v for k, v in variables.items() if v.ndim not in (1, 2)}
    if other:
        raise SavedModelImportError(
            f"generic fallback handles only matrix/vector variables; found "
            f"{ {k: v.shape for k, v in other.items()} }"
        )
    if not two_d:
        raise SavedModelImportError("generic fallback found no 2-D variables at all")

    emb_named = [k for k in two_d if _role(k, _VAR_ROLE_PATTERNS) == "embedding"]
    if len(emb_named) == 1:
        emb_name = emb_named[0]
    elif len(emb_named) > 1:
        raise SavedModelImportError(
            f"generic fallback found several embedding-like tables "
            f"{sorted(emb_named)}; cannot pick one"
        )
    else:
        emb_name = max(two_d, key=lambda k: two_d[k].shape[0])
    vocab_size, embed_dim = map(int, two_d[emb_name].shape)
    d0 = num_fields * embed_dim
    kernels = {k: v for k, v in two_d.items() if k != emb_name}

    # Depth-first shape-chaining: one ordering that consumes every kernel.
    # Branching is bounded: same-shape kernels are interchangeable, so each
    # level tries ONE candidate per distinct shape (natural-name order
    # within a shape keeps stacked layers stable), and dead (cur_dim,
    # remaining) states are memoized — without this, a dozen uniform-width
    # kernels with no valid chain would backtrack factorially.
    dead: set[tuple[int, frozenset]] = set()

    def chain(cur_dim: int, remaining: frozenset) -> list[str] | None:
        if not remaining:
            return []
        if (cur_dim, remaining) in dead:
            return None
        tried_shapes = set()
        for k in sorted(remaining, key=_natural_key):
            rows, cols = kernels[k].shape
            if rows != cur_dim or (rows, cols) in tried_shapes:
                continue
            tried_shapes.add((rows, cols))
            if not remaining - {k} and cols != 1:
                continue  # the last kernel must emit the logit
            rest = chain(cols, remaining - {k})
            if rest is not None:
                return [k] + rest
        dead.add((cur_dim, remaining))
        return None

    order = chain(d0, frozenset(kernels))
    if order is None:
        raise SavedModelImportError(
            f"dense kernels { {k: v.shape for k, v in kernels.items()} } do not "
            f"chain from F*D={d0} (num_fields={num_fields} x embed_dim="
            f"{embed_dim}) down to a 1-wide logit using every kernel"
        )

    def bias_for(kernel_name: str, width: int, used: set) -> str:
        sibling = re.sub(r"kernel|weights?$", "bias", kernel_name)
        if sibling != kernel_name and sibling in one_d and sibling not in used:
            return sibling
        by_shape = [
            k for k, v in one_d.items() if v.shape == (width,) and k not in used
        ]
        if len(by_shape) == 1:
            return by_shape[0]
        raise SavedModelImportError(
            f"no unambiguous bias of width {width} for kernel {kernel_name!r}; "
            f"candidates: {by_shape}"
        )

    mapping: dict[str, str] = {"embedding": emb_name}
    used_biases: set[str] = set()
    mlp_dims = []
    for i, k in enumerate(order):
        width = int(kernels[k].shape[1])
        b = bias_for(k, width, used_biases)
        used_biases.add(b)
        if i < len(order) - 1:
            mapping[f"mlp/{i}/w"] = k
            mapping[f"mlp/{i}/b"] = b
            mlp_dims.append(width)
        else:
            mapping["out/w"] = k
            mapping["out/b"] = b
    unused = set(one_d) - used_biases
    if unused:
        raise SavedModelImportError(
            f"generic fallback would leave vector variables unbound: "
            f"{ {k: one_d[k].shape for k in sorted(unused)} } (batch-norm "
            "stats or non-bias vectors are outside the embed+MLP shape)"
        )

    import dataclasses as dc

    generic_config = dc.replace(
        config,
        num_fields=num_fields,
        vocab_size=vocab_size,
        embed_dim=embed_dim,
        mlp_dims=tuple(mlp_dims),
    )
    return generic_config, mapping


def _check_signature_aliases(signatures, kind: str, config: ModelConfig) -> None:
    """The imported signature is the client-facing contract, but the zoo
    forward consumes fixed keys; an alias mismatch would import cleanly and
    then fail every Predict at apply time — fail fast here instead."""
    from ..models.registry import DEFAULT_SIGNATURE, ctr_signatures

    default = signatures.get(DEFAULT_SIGNATURE)
    if default is None:
        return  # no serving_default: caller serves by explicit signature
    # dense_features is intentionally NOT required: the DLRM forward
    # substitutes zeros when it is absent, so sparse-only exports serve fine.
    required = {
        s.name for s in ctr_signatures(config.num_fields)[DEFAULT_SIGNATURE].inputs
    }
    have = {s.name for s in default.inputs}
    missing = required - have
    if missing:
        raise SavedModelImportError(
            f"SavedModel serving_default inputs {sorted(have)} lack the "
            f"{kind!r} forward's required aliases {sorted(missing)}; this "
            "export's request contract does not match the model family "
            "(re-export with matching input names, or extend the importer "
            "with an alias map)"
        )


def _default_npz_cache_path(saved_model_dir) -> pathlib.Path:
    """Extraction-cache location OUTSIDE the SavedModel directory.

    Serving artifacts are commonly mounted read-only, and writing into the
    artifact both fails there and mutates the export's content/mtimes for
    every other consumer (round-1 advisor finding). The cache lives in a
    per-user temp dir keyed by the absolute SavedModel path; staleness is
    still governed by _npz_cache_fresh's mtime comparison against the
    export's own files."""
    import hashlib
    import tempfile

    root = pathlib.Path(tempfile.gettempdir()) / f"dts_tpu_sm_cache_{os.getuid()}"
    root.mkdir(mode=0o700, parents=True, exist_ok=True)
    # Fail closed against a pre-created dir in the shared /tmp namespace:
    # mkdir's mode is NOT applied when the dir already exists, and a foreign
    # owner could plant a fresh-mtime npz the importer would np.load as
    # model weights.
    st = root.stat()
    if st.st_uid != os.getuid() or (st.st_mode & 0o077):
        raise SavedModelImportError(
            f"extraction cache dir {root} is not exclusively owned by uid "
            f"{os.getuid()} (uid={st.st_uid}, mode={oct(st.st_mode & 0o777)}); "
            "refusing to trust cached weights from it"
        )
    # Key on path AND a content fingerprint (name/size/mtime of the pb and
    # every variables file): a version dir replaced wholesale (rsync/tar/mv
    # preserving build-time mtimes) must miss the old cache — the mtime-only
    # freshness test cannot see that replacement, a path-only key would
    # silently serve the previous model's weights.
    sm = pathlib.Path(saved_model_dir)
    h = hashlib.sha1(str(sm.resolve()).encode())
    for p in [sm / "saved_model.pb", *sorted((sm / "variables").glob("*"))]:
        try:
            st = p.stat()
            h.update(f"{p.name}:{st.st_size}:{st.st_mtime_ns};".encode())
        except OSError:
            continue
    return root / f"{h.hexdigest()[:24]}.npz"


def _npz_cache_fresh(saved_model_dir, npz_path) -> bool:
    """The cached extraction is valid only if it postdates every SavedModel
    artifact — an in-place re-export must trigger re-extraction, never serve
    stale weights."""
    npz_path = pathlib.Path(npz_path)
    if not npz_path.exists():
        return False
    cache_mtime = npz_path.stat().st_mtime
    root = pathlib.Path(saved_model_dir)
    # Strict <: a source touched in the same mtime tick as the cache counts
    # as newer (re-extracting costs seconds; stale weights cost correctness).
    sources = [root / "saved_model.pb", *(root / "variables").glob("variables.*")]
    return all(not p.exists() or p.stat().st_mtime < cache_mtime for p in sources)


# ----------------------------------------------------------------- import


def _graph_servable(
    saved_model_dir, meta_graph, signatures, name, version, python
) -> Servable:
    """Servable executing the export's own GraphDef (interop/graph_exec.py).

    Variables are extracted keyed by VarHandleOp shared_name (a separate
    cache from the object-path npz used for zoo binding), and the executor
    is validated with an EAGER two-row dry run at import time — an
    unsupported op fails the load with its node name, never a live request.
    """
    from .graph_exec import graph_model

    # ONE signature choice threaded through extraction, executor build, and
    # the dry-run probe (they could otherwise disagree on a multi-signature
    # export, or fail outright on an export without 'serving_default').
    if "serving_default" in meta_graph.signature_def:
        sig_name = "serving_default"
    else:
        served = [
            k for k in meta_graph.signature_def
            if not k.startswith("__")  # skip __saved_model_init_op etc.
        ]
        if not served:
            raise SavedModelImportError(
                f"{saved_model_dir} exports no servable signatures"
            )
        sig_name = sorted(served)[0]

    cache = _default_npz_cache_path(saved_model_dir)
    cache = cache.with_name(cache.stem + "-graph.npz")
    if _npz_cache_fresh(saved_model_dir, cache):
        log.info("reusing extracted graph-variables cache %s", cache)
    else:
        extract_graph_variables(
            saved_model_dir, cache, signature_name=sig_name, python=python
        )
    with np.load(cache) as npz:
        variables = {k: npz[k] for k in npz.files}

    model, params = graph_model(
        meta_graph, variables, signature_name=sig_name, name=name
    )

    import contextlib

    import jax

    from .. import codec as _codec

    sig = signatures[sig_name] if sig_name in signatures else (
        next(iter(signatures.values()))
    )
    # Placeholder shape attrs fill in what the SignatureDef leaves unknown:
    # skipping an unknown-rank input would leave its placeholder unfed and
    # fail the probe for an export the serving path handles fine.
    pnodes = {n.name: n for n in meta_graph.graph_def.node}
    probe = {}
    for spec in sig.inputs:
        shape = spec.shape
        if shape is None:
            node = pnodes.get(model.apply.input_nodes.get(spec.name, ""))
            if node is not None and "shape" in node.attr and not (
                node.attr["shape"].shape.unknown_rank
            ):
                shape = tuple(
                    None if d.size < 0 else d.size
                    for d in node.attr["shape"].shape.dim
                )
            else:
                shape = (None,)  # last resort: a flat 1-D probe
        dims = (2,) + tuple(d or 1 for d in shape[1:]) if shape else (2,)
        probe[spec.name] = np.zeros(dims, _codec.dtype_to_numpy(spec.dtype))
    from ..utils.compat import enable_x64

    ctx = enable_x64() if model.needs_x64 else contextlib.nullcontext()
    with ctx:
        outputs = model.apply(params, probe)  # eager: no compile cost
    log.info(
        "graph executor serves %s: %d variables, outputs %s",
        saved_model_dir, len(params), sorted(outputs),
    )
    return Servable(
        name=name, version=version, model=model, params=params, signatures=signatures
    )


def import_savedmodel(
    saved_model_dir,
    kind: str,
    config: ModelConfig,
    name: str = "DCN",
    version: int = 1,
    mapping: dict[str, str] | None = None,
    variables_npz=None,
    python: str = sys.executable,
    fallback: bool = True,
) -> Servable:
    """SavedModel directory -> registry-ready Servable.

    `kind`/`config` select the model-zoo family the weights belong to (the
    graph itself is not replayed — the zoo's jitted forward IS the TPU
    program; SURVEY.md §7 design stance). `variables_npz` reuses an
    already-extracted dump and skips the TF subprocess.

    The import boundary (VERDICT r2 item 7): when the export's weights do
    not bind to the requested family and `fallback` is on, the importer
    tries the `generic` embed+MLP family with the architecture inferred
    from the export's own shapes; when that fails too, the error names the
    supported families and both failure reasons — an actionable rejection,
    not silence. Exports beyond "weights onto a native forward" (custom
    GraphDef ops) are out of scope by design; the reference delegated that
    to tensorflow_model_server's graph executor (meta_graph.proto:31-87).
    """
    import jax

    meta_graph = serve_meta_graph(read_saved_model(saved_model_dir))
    signatures = signatures_from_meta_graph(meta_graph)
    if kind == "graph":
        # Explicit graph-executor serving: run the export's own GraphDef
        # (interop/graph_exec.py) instead of binding weights onto a zoo
        # family.
        return _graph_servable(
            saved_model_dir, meta_graph, signatures, name, version, python
        )
    _check_signature_aliases(signatures, kind, config)

    if variables_npz is None:
        # Honor a FRESH cache shipped inside the artifact (a deliberate
        # pre-extraction); anything needing (re-)extraction goes to the
        # out-of-artifact default — the artifact dir may be a read-only
        # mount and must never be mutated by the importer.
        in_dir = pathlib.Path(saved_model_dir) / "variables_extracted.npz"
        if in_dir.exists() and _npz_cache_fresh(saved_model_dir, in_dir):
            variables_npz = in_dir
            log.info("reusing extracted variables cache %s", variables_npz)
        else:
            variables_npz = _default_npz_cache_path(saved_model_dir)
            if _npz_cache_fresh(saved_model_dir, variables_npz):
                log.info("reusing extracted variables cache %s", variables_npz)
            else:
                extract_variables(saved_model_dir, variables_npz, python=python)
    with np.load(variables_npz) as npz:
        variables = {k: npz[k] for k in npz.files}

    model = build_model(kind, config)
    template = jax.tree.map(np.asarray, model.init(jax.random.PRNGKey(0)))
    try:
        params = map_variables(variables, template, mapping)
    except SavedModelImportError as exc:
        if not fallback or mapping or kind == "generic":
            raise
        try:
            generic_config, generic_mapping = infer_generic_architecture(
                variables, signatures, config
            )
            model = build_model("generic", generic_config)
            template = jax.tree.map(np.asarray, model.init(jax.random.PRNGKey(0)))
            params = map_variables(variables, template, generic_mapping)
        except SavedModelImportError as exc2:
            # Last resort: execute the export's own graph. Slower than a
            # zoo forward (no host fold / transfer compression, x64 ids)
            # but serves ANY architecture within the executor's op set.
            try:
                servable = _graph_servable(
                    saved_model_dir, meta_graph, signatures, name, version, python
                )
            except Exception as exc3:  # noqa: BLE001 — fold into the ranked error
                from ..models.base import model_kinds

                raise SavedModelImportError(
                    f"export at {saved_model_dir} could not be served.\n"
                    f"- as requested kind {kind!r}: {exc}\n"
                    f"- as the generic embed+MLP fallback: {exc2}\n"
                    f"- via the GraphDef executor: {exc3}\n"
                    f"Native families: {sorted(model_kinds())}. Re-export in "
                    "one of these architectures, pass an explicit "
                    "{param-path: variable-name} mapping, or keep the "
                    "export's graph inside the executor's documented op set "
                    "(interop/graph_exec.py)."
                ) from exc
            log.warning(
                "export did not bind to %r (%s) nor the generic fallback "
                "(%s); serving via the GraphDef executor", kind, exc, exc2,
            )
            return servable
        log.warning(
            "export did not bind to %r (%s); serving via the generic "
            "embed+MLP fallback: num_fields=%d embed_dim=%d mlp_dims=%s",
            kind, exc, generic_config.num_fields, generic_config.embed_dim,
            generic_config.mlp_dims,
        )
    return Servable(
        name=name, version=version, model=model, params=params, signatures=signatures
    )


def main(argv=None) -> None:
    import argparse

    from ..train.checkpoint import save_servable

    parser = argparse.ArgumentParser(
        description="Convert a TF SavedModel into a native servable checkpoint"
    )
    parser.add_argument("saved_model_dir")
    parser.add_argument("out_dir")
    parser.add_argument("--kind", default="dcn_v2")
    parser.add_argument("--name", default="DCN")
    parser.add_argument("--version", type=int, default=1)
    parser.add_argument("--num-fields", type=int, default=43)
    parser.add_argument("--vocab-size", type=int, default=1 << 20)
    parser.add_argument("--embed-dim", type=int, default=16)
    parser.add_argument("--mapping", help="JSON file: {param-path: variable-name}")
    args = parser.parse_args(argv)

    config = ModelConfig(
        name=args.name,
        num_fields=args.num_fields,
        vocab_size=args.vocab_size,
        embed_dim=args.embed_dim,
    )
    mapping = json.loads(pathlib.Path(args.mapping).read_text()) if args.mapping else None
    servable = import_savedmodel(
        args.saved_model_dir, args.kind, config,
        name=args.name, version=args.version, mapping=mapping,
    )
    save_servable(args.out_dir, servable, kind=args.kind)
    print(f"imported {args.name} v{args.version} ({args.kind}) -> {args.out_dir}; "
          f"signatures: {sorted(servable.signatures)}")


if __name__ == "__main__":
    main()
