"""Sampled request logging (TF-Serving's LoggingConfig surface).

`tensorflow_model_server` can log a sample of live traffic as
PredictionLog records (model_config LoggingConfig: a log-collector sink +
sampling_config.sampling_rate); the logs are the standard input for
building warmup files, offline replay, and drift analysis. This is the
in-tree equivalent: sampled requests are framed as PredictionLog TFRecords
(serving/warmup.py writes the framing, so the output is DIRECTLY usable as
`assets.extra/tf_serving_warmup_requests` — serve traffic today, warm
tomorrow's version with it).

Design constraints, in order:
- The hot path must never block on disk: sampling serializes the request
  (bytes it may already have) and enqueues onto a BOUNDED queue; a full
  queue drops the record and counts it (`dropped`), the way upstream's
  log collector sheds rather than backpressures serving.
- The writer thread owns the file and the PredictionLog assembly (the
  proto wrap is deferred off the request thread).
- Request-only logs (PredictLog.response left empty): warmup replay
  ignores responses by design, and doubling the bytes for a field the
  consumers skip is the wrong default. (Upstream can log both; the
  schema here is identical, so adding responses later is additive.)
"""

from __future__ import annotations

import logging
import queue
import random
import threading

from .warmup import frame_tfrecord

log = logging.getLogger("dts_tpu.request_log")

_KIND_FIELDS = {
    "predict": "predict_log",
    "classify": "classify_log",
    "regress": "regress_log",
    "multi_inference": "multi_inference_log",
}


class RequestLogger:
    """Sampled PredictionLog TFRecord writer with a bounded queue."""

    def __init__(
        self,
        path,
        sampling_rate: float = 0.01,
        max_queued: int = 256,
        rng: random.Random | None = None,
    ):
        if not (0.0 <= sampling_rate <= 1.0):
            raise ValueError(f"sampling_rate must be in [0, 1], got {sampling_rate}")
        self.path = path
        self.sampling_rate = sampling_rate
        self.written = 0
        self.dropped = 0
        self._rng = rng or random.Random()
        self._queue: queue.Queue = queue.Queue(maxsize=max_queued)
        self._file = open(path, "ab")
        self._thread = threading.Thread(
            target=self._loop, name="request-log", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- hot path

    def maybe_log(self, kind: str, request) -> None:
        """Sample and enqueue one request; never blocks, never raises."""
        try:
            if self._rng.random() >= self.sampling_rate:
                return
            payload = request.SerializeToString()
            try:
                self._queue.put_nowait((kind, payload))
            except queue.Full:
                self.dropped += 1
        except Exception:  # noqa: BLE001 — logging must never cost a request
            log.exception("request sampling failed")

    def stats(self) -> dict:
        """Written/dropped/queued accounting for /monitoring: a log queue
        shedding under load must be observable without grepping stderr."""
        return {
            "path": str(self.path),
            "sampling_rate": self.sampling_rate,
            "written": self.written,
            "dropped": self.dropped,
            "queued": self._queue.qsize(),
        }

    # --------------------------------------------------------------- writer

    def _write_record(self, kind: str, payload: bytes) -> None:
        """Frame + write one sampled record (writer thread, or close()'s
        residual drain — never the request path)."""
        from ..proto import serving_apis_pb2 as apis

        try:
            plog = apis.PredictionLog()
            getattr(plog, _KIND_FIELDS[kind]).request.MergeFromString(payload)
            # One write + flush per record: a crash/SIGKILL can
            # truncate at most the FINAL record, never interleave.
            self._file.write(frame_tfrecord(plog.SerializeToString()))
            self._file.flush()
            self.written += 1
        except Exception:  # noqa: BLE001 — keep draining
            log.exception("request-log write failed")

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            self._write_record(*item)

    def close(self) -> None:
        """Flush every pending record, then close; idempotent.

        The sentinel rides the FIFO queue behind any pending entries, so
        the writer drains them before exiting; records that slipped in
        behind the sentinel (or are left behind an already-exited writer)
        are written synchronously here rather than discarded — sampled
        records already accepted are evidence, and close() is the last
        chance to keep them. A WEDGED writer that outlives the join
        timeout keeps ownership of the file: closing it under a live
        writer would interleave/corrupt the record stream, so close()
        leaves the (daemon) thread to finish and reports what is still
        queued — a later close() retries."""
        if self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=10)
        if self._thread.is_alive():
            log.warning(
                "request log %s: writer still busy after close timeout; "
                "leaving the file to it (%d records queued)",
                self.path, self._queue.qsize(),
            )
            return
        if not self._file.closed:
            # Residual drain: anything still queued (entries enqueued after
            # the sentinel was inserted) flushes before the file closes.
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    self._write_record(*item)
            self._file.close()
        if self.dropped:
            log.warning(
                "request log %s dropped %d records (queue full)",
                self.path, self.dropped,
            )
