"""gRPC frontend: the TPU-VM shim exposing PredictionService on the DCN edge.

The reference's serving endpoint was `tensorflow_model_server` on port 9999
(DCNClient.java:28); this is its in-tree replacement. A thin adapter maps
ServiceError codes onto grpc status codes and delegates everything else to
PredictionServiceImpl. Handler threads block on batcher futures, so the
thread pool size bounds in-flight RPCs while the batcher thread serializes
device work.
"""

from __future__ import annotations

import argparse
import logging
from concurrent import futures

import grpc
import jax

from ..models import ModelConfig, Servable, ServableRegistry, build_model, ctr_signatures
from ..proto import add_PredictionServiceServicer_to_server
from .batcher import DynamicBatcher
from .service import PredictionServiceImpl, ServiceError

log = logging.getLogger("dts_tpu.server")


def _status(code_name: str) -> grpc.StatusCode:
    return getattr(grpc.StatusCode, code_name, grpc.StatusCode.UNKNOWN)


class GrpcPredictionService:
    """grpc servicer adapter; safe against handler-thread exceptions."""

    def __init__(self, impl: PredictionServiceImpl):
        self.impl = impl

    def _call(self, fn, request, context):
        try:
            return fn(request)
        except ServiceError as e:
            context.abort(_status(e.code), str(e))
        except Exception as e:  # internal bug: surface as INTERNAL, keep serving
            log.exception("internal error serving %s", fn.__name__)
            context.abort(grpc.StatusCode.INTERNAL, f"internal error: {e}")

    def Predict(self, request, context):
        return self._call(self.impl.predict, request, context)

    def Classify(self, request, context):
        return self._call(self.impl.classify, request, context)

    def Regress(self, request, context):
        return self._call(self.impl.regress, request, context)

    def MultiInference(self, request, context):
        return self._call(self.impl.multi_inference, request, context)

    def GetModelMetadata(self, request, context):
        return self._call(self.impl.get_model_metadata, request, context)


def create_server(
    impl: PredictionServiceImpl,
    address: str = "127.0.0.1:0",
    max_workers: int = 16,
) -> tuple[grpc.Server, int]:
    """Build (not start) a server; returns (server, bound_port)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="rpc"),
        options=[
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ],
    )
    add_PredictionServiceServicer_to_server(GrpcPredictionService(impl), server)
    port = server.add_insecure_port(address)
    if port == 0:
        raise RuntimeError(f"could not bind {address}")
    return server, port


def load_demo_servable(
    registry: ServableRegistry,
    kind: str = "dcn_v2",
    name: str = "DCN",
    version: int = 1,
    seed: int = 0,
    **config_overrides,
) -> Servable:
    """Build + register a randomly-initialized servable (demo/bench path;
    production params come from train/checkpoint.py)."""
    config = ModelConfig(name=name, **config_overrides)
    model = build_model(kind, config)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    jax.block_until_ready(params)
    dense = config.num_dense_features if kind == "dlrm" else None
    servable = Servable(
        name=name,
        version=version,
        model=model,
        params=params,
        signatures=ctr_signatures(config.num_fields, with_dense=dense),
    )
    registry.load(servable)
    return servable


def serve(argv=None) -> None:
    parser = argparse.ArgumentParser(description="TPU-native PredictionService")
    parser.add_argument("--port", type=int, default=9999)  # reference default, DCNClient.java:28
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--model-kind", default="dcn_v2")
    parser.add_argument("--model-name", default="DCN")
    parser.add_argument("--num-fields", type=int, default=43)
    parser.add_argument("--max-workers", type=int, default=16)
    parser.add_argument("--max-wait-us", type=int, default=200)
    parser.add_argument("--warmup", action="store_true", help="precompile bucket ladder")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    registry = ServableRegistry()
    batcher = DynamicBatcher(max_wait_us=args.max_wait_us).start()
    impl = PredictionServiceImpl(registry, batcher)
    servable = load_demo_servable(
        registry, kind=args.model_kind, name=args.model_name, num_fields=args.num_fields
    )
    if args.warmup:
        log.info("warming bucket ladder %s", batcher.buckets)
        batcher.warmup(servable)
    server, port = create_server(impl, f"{args.host}:{args.port}", args.max_workers)
    server.start()
    log.info("PredictionService on %s:%d (model=%s kind=%s, devices=%s)",
             args.host, port, args.model_name, args.model_kind, jax.devices())
    server.wait_for_termination()


if __name__ == "__main__":
    serve()
