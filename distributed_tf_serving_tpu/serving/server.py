"""gRPC frontend: the TPU-VM shim exposing PredictionService on the DCN edge.

The reference's serving endpoint was `tensorflow_model_server` on port 9999
(DCNClient.java:28); this is its in-tree replacement. A thin adapter maps
ServiceError codes onto grpc status codes, records per-RPC latency/outcome
metrics, and delegates everything else to PredictionServiceImpl. Handler
threads block on batcher futures, so the thread pool size bounds in-flight
RPCs while the batcher thread serializes device work.

CLI (`python -m distributed_tf_serving_tpu.serving.server`) supports the
full knob set via flags or a TOML config (utils/config.py), serves either a
demo-initialized model or a training checkpoint, and optionally shards
execution over a device mesh.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import signal
import threading
import time
from concurrent import futures

import grpc
import jax

from ..models import ModelConfig, Servable, ServableRegistry, build_model, ctr_signatures
from ..proto.service_grpc import (
    KEEPALIVE_SERVER_OPTIONS,
    LARGE_MESSAGE_CHANNEL_OPTIONS,
)
from ..proto import (
    add_HealthServicer_to_server,
    add_ModelServiceServicer_to_server,
    add_PredictionServiceServicer_to_server,
)
from ..proto import health as health_proto
from .. import codec
from ..utils.config import ServerConfig, load_config
from ..utils.metrics import ServerMetrics
from ..utils import tracing
from ..utils.tracing import request_trace
from . import lifecycle as lifecycle_mod
from . import overload as overload_mod
from ..ops import autotune as kernels_mod
from .batcher import DynamicBatcher
from .service import PredictionServiceImpl, ServiceError

log = logging.getLogger("dts_tpu.server")


def _status(code_name: str) -> grpc.StatusCode:
    return getattr(grpc.StatusCode, code_name, grpc.StatusCode.UNKNOWN)


def _model_of(request) -> str | None:
    """The resolved model label for metrics/tracing (None when the request
    shape carries no top-level model_spec, e.g. MultiInference)."""
    return getattr(getattr(request, "model_spec", None), "name", "") or None


def _traceparent_of(context) -> str | None:
    """The W3C traceparent from the RPC's invocation metadata (both sync
    and aio contexts expose it as (key, value) pairs); None when absent.
    Only called when tracing is enabled."""
    try:
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                return value
    except Exception:  # noqa: BLE001 — tracing must never fail an RPC
        return None
    return None


def _criticality_of(context) -> str | None:
    """The request's criticality lane from invocation metadata
    (x-dts-criticality). Only scanned while a plane that CONSUMES the
    lane is armed — the overload controller (lane-ordered shedding) or
    the lifecycle controller (probe-lane-first canary routing) — two
    module-bool reads otherwise."""
    if not (overload_mod.active() or lifecycle_mod.active()):
        return None
    try:
        for key, value in context.invocation_metadata() or ():
            if key == overload_mod.CRITICALITY_KEY:
                return overload_mod.normalize_criticality(value)
    except Exception:  # noqa: BLE001 — a metadata quirk must not fail the RPC
        return None
    return None


def _score_wire_of(context) -> bool:
    """True when the request opted into the int8 score response wire
    (x-dts-score-wire: int8) AND a kernels plane armed it — one module
    bool read per RPC otherwise (the overload/lifecycle active()
    precedent)."""
    if not kernels_mod.wire_active():
        return False
    try:
        for key, value in context.invocation_metadata() or ():
            if key == kernels_mod.SCORE_WIRE_KEY:
                return str(value).strip().lower() == "int8"
    except Exception:  # noqa: BLE001 — a metadata quirk must not fail the RPC
        return False
    return False


def _stream_chunk_of(context) -> int | None:
    """Per-request sub-batch-size override for PredictStream from the
    x-dts-stream-chunk metadata key (candidates per sub-batch; the server
    still clamps the resulting chunk count). None = use the configured
    stream_chunk_candidates default."""
    try:
        for key, value in context.invocation_metadata() or ():
            if key == "x-dts-stream-chunk":
                return max(int(value), 0) or None
    except Exception:  # noqa: BLE001 — a malformed hint must not fail the RPC
        return None
    return None


def _input_crc_of(context, impl) -> str | None:
    """The client's x-dts-input-crc wire-integrity stamp (ISSUE 20), or
    None. Only scanned while the impl's integrity plane (wire layer) is
    armed — two attribute reads per RPC otherwise."""
    integ = impl.integrity
    if integ is None or not integ.config.wire_checksums:
        return None
    try:
        for key, value in context.invocation_metadata() or ():
            if key == codec.CRC_INPUT_MD:
                return str(value)
    except Exception:  # noqa: BLE001 — a metadata quirk must not fail the RPC
        return None
    return None


def _stamp_response_crc(impl, context, resp) -> None:
    """x-dts-score-crc trailing-metadata stamp over the encoded response
    tensors (ISSUE 20), shared by both transports. Advisory: a stamping
    failure must never fail a good response, and an armed overload
    plane's degraded/pushback trailing metadata (set later on the same
    context) wins the slot — the client treats an absent stamp as
    "server didn't verify", exactly like a plane-less server."""
    try:
        sidecar = impl.response_crc_sidecar(resp)
        if sidecar:
            context.set_trailing_metadata(((codec.CRC_SCORE_MD, sidecar),))
    except Exception:  # noqa: BLE001 — advisory, never fatal
        pass


def _push_overload_metadata(context, exc: ServiceError | None) -> None:
    """Overload-plane trailing metadata, shared by both transports: the
    retry-after-ms pushback hint on refusals, and the degraded marker on
    brownout stale-served successes (exc None). set_trailing_metadata
    exists on both sync and aio contexts and is a no-op cost when the
    plane is off (callers gate on overload.active())."""
    try:
        if exc is not None:
            ra = getattr(exc, "retry_after_ms", None)
            if ra:
                context.set_trailing_metadata(
                    ((overload_mod.RETRY_AFTER_KEY, str(int(ra))),)
                )
        else:
            degraded = overload_mod.consume_degraded()
            if degraded:
                context.set_trailing_metadata(
                    ((overload_mod.DEGRADED_KEY, degraded),)
                )
    except Exception:  # noqa: BLE001 — hints are advisory, never fatal
        pass


# Initial-metadata peer-role stamp (ISSUE 18 satellite): traced callers
# label their client.rpc span's resolved peer (router vs replica) from
# this, so stitched fleet trees name each hop without guessing from
# ports. INITIAL metadata — trailing already carries the overload and
# degraded markers. Only sent on traced requests: the disabled hot path
# stays one enabled() read.
_PEER_ROLE_KEY = "x-dts-peer-role"


def _send_peer_role(context) -> None:
    """Sync-transport stamp (aio contexts need `await` — inlined there)."""
    try:
        context.send_initial_metadata(((_PEER_ROLE_KEY, "replica"),))
    except Exception:  # noqa: BLE001 — advisory only
        pass


class _SyncServicerBase:
    """Shared adapter plumbing for sync servicers: ServiceError -> grpc
    status mapping + per-RPC metrics (+ the per-request server root span
    when tracing is on)."""

    def __init__(self, impl: PredictionServiceImpl, metrics: ServerMetrics | None = None):
        self.impl = impl
        self.metrics = metrics or ServerMetrics()

    def _call(self, name: str, fn, request, context):
        t0 = time.perf_counter()
        ok = False
        model = _model_of(request)
        overload_on = overload_mod.active()
        if overload_on:
            # Clear any degraded marker a failed PREVIOUS request left in
            # this handler thread's context (markers are consumed only on
            # the success path).
            overload_mod.consume_degraded()
        if tracing.enabled():
            # Server-side LOCAL ROOT: adopts the client's trace id (and
            # parents onto the exact shard-attempt span that carried the
            # RPC) when a traceparent arrived; a fresh trace otherwise.
            span_ctx = tracing.start_root(
                f"server.{name}",
                traceparent=_traceparent_of(context),
                attrs={"entrypoint": name, **({"model": model} if model else {})},
            )
            _send_peer_role(context)
        else:
            span_ctx = None
        try:
            if span_ctx is not None:
                with span_ctx:
                    resp = fn(request)
            else:
                resp = fn(request)
            ok = True
            if overload_on:
                # Brownout stale-serves announce themselves in trailing
                # metadata so callers can tell degraded from fresh.
                _push_overload_metadata(context, None)
            return resp
        except ServiceError as e:
            if overload_on:
                # Overload refusals carry the retry-after-ms pushback hint
                # the client's failover backoff honors.
                _push_overload_metadata(context, e)
            context.abort(_status(e.code), str(e))
        except Exception as e:  # internal bug: surface as INTERNAL, keep serving
            log.exception("internal error serving %s", name)
            context.abort(grpc.StatusCode.INTERNAL, f"internal error: {e}")
        finally:
            self.metrics.observe(name, time.perf_counter() - t0, ok, model=model)

    def _call_stream(self, name: str, fn, request, context):
        """_call for server-streaming RPCs: `fn(request)` returns a chunk
        generator; the same error mapping / metrics / tracing wrap the
        whole stream (one observe per stream, error status aborts
        mid-stream — grpc sends already-yielded chunks first)."""
        t0 = time.perf_counter()
        ok = False
        model = _model_of(request)
        overload_on = overload_mod.active()
        if overload_on:
            overload_mod.consume_degraded()
        if tracing.enabled():
            span_ctx = tracing.start_root(
                f"server.{name}",
                traceparent=_traceparent_of(context),
                attrs={"entrypoint": name, **({"model": model} if model else {})},
            )
            _send_peer_role(context)
        else:
            span_ctx = None
        try:
            if span_ctx is not None:
                with span_ctx:
                    yield from fn(request)
            else:
                yield from fn(request)
            ok = True
            if overload_on:
                _push_overload_metadata(context, None)
        except ServiceError as e:
            if overload_on:
                _push_overload_metadata(context, e)
            context.abort(_status(e.code), str(e))
        except Exception as e:  # internal bug: surface as INTERNAL, keep serving
            log.exception("internal error serving %s", name)
            context.abort(grpc.StatusCode.INTERNAL, f"internal error: {e}")
        finally:
            self.metrics.observe(name, time.perf_counter() - t0, ok, model=model)


def _deadline_of(context) -> float | None:
    """The client's remaining budget from the RPC context (None = no
    deadline), threaded into the impl so the batcher can shed expired work
    instead of burning its fixed 120s bound on an abandoned request."""
    remaining = context.time_remaining()
    # grpc returns None when the client set no deadline; some transports
    # report float('inf') — both mean "no client bound".
    if remaining is None or remaining == float("inf"):
        return None
    return remaining


class GrpcPredictionService(_SyncServicerBase):
    """grpc servicer adapter: error mapping + per-RPC metrics. The three
    batching RPCs propagate the client deadline into the impl."""

    def Predict(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        int8_wire = _score_wire_of(context)
        input_crc = _input_crc_of(context, self.impl)

        def handler(req):
            resp = self.impl.predict(
                req, deadline_s=deadline_s, criticality=crit,
                int8_wire=int8_wire, input_crc=input_crc,
            )
            if self.impl.integrity is not None:
                _stamp_response_crc(self.impl, context, resp)
            return resp

        return self._call("Predict", handler, request, context)

    def Classify(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        return self._call(
            "Classify",
            lambda req: self.impl.classify(
                req, deadline_s=deadline_s, criticality=crit
            ),
            request, context,
        )

    def Regress(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        return self._call(
            "Regress",
            lambda req: self.impl.regress(
                req, deadline_s=deadline_s, criticality=crit
            ),
            request, context,
        )

    def MultiInference(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        return self._call(
            "MultiInference",
            lambda req: self.impl.multi_inference(
                req, deadline_s=deadline_s, criticality=crit
            ),
            request, context,
        )

    def GetModelMetadata(self, request, context):
        return self._call("GetModelMetadata", self.impl.get_model_metadata, request, context)

    def PredictStream(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        chunk = _stream_chunk_of(context)
        return self._call_stream(
            "PredictStream",
            lambda req: self.impl.predict_stream(
                req, deadline_s=deadline_s, criticality=crit, chunk=chunk
            ),
            request, context,
        )


class GrpcModelService(_SyncServicerBase):
    """tensorflow.serving.ModelService adapter (sync): status + reload.
    Shares the impl's registry and the server's metrics/error mapping."""

    def GetModelStatus(self, request, context):
        return self._call("GetModelStatus", self.impl.get_model_status, request, context)

    def HandleReloadConfigRequest(self, request, context):
        return self._call(
            "HandleReloadConfigRequest", self.impl.handle_reload_config, request, context
        )


# Trailing-metadata key naming WHY a health Check answered NOT_SERVING
# ("draining" | "quarantined" | "starting"): the fan-out client and the
# fleet router steer a draining replica straight to the DRAINING
# scoreboard state instead of cycling the rebuilding retry window.
HEALTH_REASON_METADATA_KEY = "x-dts-health-reason"


class GrpcHealthService:
    """grpc.health.v1 Health over the serving state (proto/health.py glue;
    standard health-checking clients and the fan-out client's half-open
    probes both speak it):

    - service "" (the whole server): SERVING once the load+warmup phase
      completed (impl.warmup_complete — build_stack flips it) AND at least
      one model has a ready version; NOT_SERVING before — a server still
      compiling its bucket ladder must not receive traffic.
    - service "<model>": SERVING when the registry holds a ready version;
      NOT_SERVING when the server is CONFIGURED for the model (a watcher or
      lifecycle owns it) but no version landed yet; grpc NOT_FOUND for
      names this server was never told about (the health spec's
      unknown-service answer).
    """

    # How often Watch re-evaluates serving state. Each sync watcher holds
    # a thread-pool worker for the stream's lifetime, so this is a
    # router-tier surface (a handful of subscribers), not an edge one.
    watch_poll_s = 0.2

    def __init__(self, impl: PredictionServiceImpl):
        self.impl = impl

    def _status(self, service: str) -> int | None:
        served = self.impl.registry.models()
        if not service:
            ready = any(served.values())
            # A draining server (SIGTERM received, GracefulShutdown in
            # progress) reports NOT_SERVING so load balancers stop routing
            # to it while accepted work finishes. So does a QUARANTINED
            # one (recovery plane mid quarantine/reinit/replay): clients
            # failover via the scoreboard until the rebuilt executor has
            # drained its replay.
            recovery = getattr(self.impl, "recovery", None)
            return (
                health_proto.SERVING
                if (self.impl.warmup_complete and ready
                    and not getattr(self.impl, "draining", False)
                    and not (recovery is not None and recovery.not_serving()))
                else health_proto.NOT_SERVING
            )
        if served.get(service):
            return health_proto.SERVING
        # Same "configured" definition as GetModelStatus's START-vs-
        # NOT_FOUND split, so the two probe surfaces can never disagree.
        return (
            health_proto.NOT_SERVING
            if self.impl.is_configured(service)
            else None
        )

    def _reason(self, service: str) -> str:
        """WHY the overall service is NOT_SERVING, as the
        x-dts-health-reason trailer: "draining" (GracefulShutdown — the
        process is leaving; steer away and do NOT re-probe it on the
        rebuild cadence), "quarantined" (recovery cycle — it comes back),
        or "starting" (warmup not finished). Empty for per-model checks,
        whose NOT_SERVING already means "configured, no version"."""
        if service:
            return ""
        if getattr(self.impl, "draining", False):
            return "draining"
        recovery = getattr(self.impl, "recovery", None)
        if recovery is not None and recovery.not_serving():
            return "quarantined"
        return "starting"

    def _check_response(self, request, context):
        st = self._status(request.service)
        if st is None:
            return None
        if st == health_proto.NOT_SERVING:
            reason = self._reason(request.service)
            if reason:
                context.set_trailing_metadata(
                    ((HEALTH_REASON_METADATA_KEY, reason),)
                )
        return health_proto.HealthCheckResponse(status=st)

    def Check(self, request, context):
        resp = self._check_response(request, context)
        if resp is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"unknown service {request.service!r}",
            )
        return resp

    def Watch(self, request, context):
        """grpc.health.v1 streaming Watch: current status immediately,
        then a message per CHANGE. Per the health spec an unknown service
        streams SERVICE_UNKNOWN (no abort) so the watcher sees it appear
        later. Fleet routers subscribe here instead of half-open
        polling."""
        last = None
        while context.is_active():
            st = self._status(request.service)
            if st is None:
                st = health_proto.SERVICE_UNKNOWN
            if st != last:
                last = st
                yield health_proto.HealthCheckResponse(status=st)
            time.sleep(self.watch_poll_s)

    def watch_once(self, request, context):  # pragma: no cover - hook
        """Test seam: one Watch evaluation without the stream loop."""
        st = self._status(request.service)
        return health_proto.SERVICE_UNKNOWN if st is None else st


class AioGrpcHealthService(GrpcHealthService):
    """Same status logic on the coroutine server (context.abort awaits)."""

    async def Check(self, request, context):
        resp = self._check_response(request, context)
        if resp is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"unknown service {request.service!r}",
            )
        return resp

    async def Watch(self, request, context):
        import asyncio

        last = None
        while True:
            st = self._status(request.service)
            if st is None:
                st = health_proto.SERVICE_UNKNOWN
            if st != last:
                last = st
                yield health_proto.HealthCheckResponse(status=st)
            await asyncio.sleep(self.watch_poll_s)


def _add_uds_port(server, uds_path: str) -> None:
    """Bind the server to a Unix-domain socket NEXT TO its TCP port
    (transport-floor satellite, ISSUE 9): co-located fan-out clients dial
    `unix:<path>` and skip the TCP/loopback stack — no checksums, no
    Nagle/ACK machinery, smaller per-message syscall cost. A stale socket
    file from a previous process is removed first (grpc refuses to bind
    over it)."""
    import os as _os

    try:
        if _os.path.exists(uds_path):
            _os.unlink(uds_path)
    except OSError:
        pass  # bind below gives the actionable error
    if server.add_insecure_port(f"unix:{uds_path}") == 0:
        raise RuntimeError(f"could not bind unix:{uds_path}")


def create_server(
    impl: PredictionServiceImpl,
    address: str = "127.0.0.1:0",
    max_workers: int = 16,
    metrics: ServerMetrics | None = None,
    credentials: "grpc.ServerCredentials | None" = None,
    uds_path: str | None = None,
) -> tuple[grpc.Server, int]:
    """Build (not start) a server; returns (server, bound_port).
    `credentials` switches the port to TLS (ssl_server_credentials — the
    --ssl-config-file surface; see load_ssl_credentials). `uds_path`
    additionally binds a plaintext Unix-domain socket for co-located
    clients ([transport] uds_path)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="rpc"),
        options=list(LARGE_MESSAGE_CHANNEL_OPTIONS) + list(KEEPALIVE_SERVER_OPTIONS),
    )
    servicer = GrpcPredictionService(impl, metrics)
    add_PredictionServiceServicer_to_server(servicer, server)
    # Same port, second service — exactly tensorflow_model_server's layout.
    add_ModelServiceServicer_to_server(GrpcModelService(impl, servicer.metrics), server)
    # Third service: grpc.health.v1 (standard probes + client half-open
    # probing) — NOT_SERVING until warmup completes, per-model afterward.
    add_HealthServicer_to_server(GrpcHealthService(impl), server)
    if credentials is not None:
        if uds_path:
            # The UDS listener is plaintext: binding it next to a TLS/mTLS
            # TCP port would silently open an unauthenticated side door
            # for any local process that can reach the socket file —
            # refuse the combination instead of downgrading.
            raise ValueError(
                "[transport] uds_path cannot be combined with "
                "--ssl-config-file: the unix socket is plaintext and "
                "would bypass the TLS/mTLS the TCP port enforces"
            )
        port = server.add_secure_port(address, credentials)
    else:
        port = server.add_insecure_port(address)
    if port == 0:
        raise RuntimeError(f"could not bind {address}")
    if uds_path:
        _add_uds_port(server, uds_path)
    return server, port


def load_ssl_credentials(path) -> "grpc.ServerCredentials":
    """tensorflow_model_server's --ssl_config_file: a text-format SSLConfig
    whose fields carry the PEM CONTENTS inline (upstream convention).
    client_verify=true demands a client certificate chained to custom_ca
    (mTLS); custom_ca without client_verify merely offers it."""
    import pathlib

    from google.protobuf import text_format

    from ..proto import serving_apis_pb2 as apis

    cfg = text_format.Parse(pathlib.Path(path).read_text(), apis.SSLConfig())
    if not cfg.server_key or not cfg.server_cert:
        raise ValueError(
            f"{path}: SSLConfig requires both server_key and server_cert "
            "(PEM contents inline)"
        )
    if cfg.client_verify and not cfg.custom_ca:
        # grpc-python itself rejects require_client_auth without root
        # certificates ("Illegal to require client auth without providing
        # root certificates!"); surface the config-level fix instead.
        raise ValueError(
            f"{path}: client_verify requires custom_ca (the CA that signs "
            "client certificates; grpc refuses client auth without roots)"
        )
    return grpc.ssl_server_credentials(
        [(cfg.server_key.encode(), cfg.server_cert.encode())],
        root_certificates=cfg.custom_ca.encode() if cfg.custom_ca else None,
        require_client_auth=cfg.client_verify,
    )


class _AioServicerBase:
    """Shared adapter plumbing for grpc.aio servicers: ServiceError ->
    status mapping (coroutine- and plain-callable-aware) + per-RPC
    metrics. Mirrors _SyncServicerBase."""

    def __init__(self, impl: PredictionServiceImpl, metrics: ServerMetrics | None = None):
        self.impl = impl
        self.metrics = metrics or ServerMetrics()

    async def _call(self, name: str, fn, request, context):
        t0 = time.perf_counter()
        ok = False
        model = _model_of(request)
        overload_on = overload_mod.active()
        if overload_on:
            overload_mod.consume_degraded()  # clear a failed predecessor's marker
        if tracing.enabled():
            span_ctx = tracing.start_root(
                f"server.{name}",
                traceparent=_traceparent_of(context),
                attrs={"entrypoint": name, **({"model": model} if model else {})},
            )
            try:
                await context.send_initial_metadata(
                    ((_PEER_ROLE_KEY, "replica"),)
                )
            except Exception:  # noqa: BLE001 — advisory only
                pass
        else:
            span_ctx = None
        try:
            if span_ctx is not None:
                # Sync `with` is correct across awaits here: contextvars
                # are coroutine-scoped, so the span stays current through
                # the await and resets on exit.
                with span_ctx:
                    resp = fn(request)
                    if hasattr(resp, "__await__"):
                        resp = await resp
            else:
                resp = fn(request)
                if hasattr(resp, "__await__"):
                    resp = await resp
            ok = True
            if overload_on:
                _push_overload_metadata(context, None)
            return resp
        except ServiceError as e:
            if overload_on:
                _push_overload_metadata(context, e)
            await context.abort(_status(e.code), str(e))
        except grpc.aio.AbortError:
            raise
        except Exception as e:  # internal bug: surface as INTERNAL, keep serving
            log.exception("internal error serving %s", name)
            await context.abort(grpc.StatusCode.INTERNAL, f"internal error: {e}")
        finally:
            self.metrics.observe(name, time.perf_counter() - t0, ok, model=model)


class AioGrpcPredictionService(_AioServicerBase):
    """grpc.aio servicer adapter: one event-loop thread carries every
    in-flight RPC instead of a handler thread each.

    On a single-core serving host the thread-per-RPC model's GIL hand-offs
    and context switches are a first-order cost (round-3 load experiment:
    ~15% of achievable QPS at 64-way concurrency); the coroutine model keeps
    the hot paths on one thread and awaits the batcher future:
    Predict/Classify/Regress all ride their _async impl variants.
    GetModelMetadata runs its (cheap, synchronous) body inline;
    MultiInference — whose sub-calls block on batcher futures for a
    client-controlled deadline — dispatches to a worker thread so it can
    never stall the loop that carries every other in-flight RPC.
    """

    async def Predict(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        int8_wire = _score_wire_of(context)
        input_crc = _input_crc_of(context, self.impl)

        async def handler(req):
            resp = await self.impl.predict_async(
                req, deadline_s=deadline_s, criticality=crit,
                int8_wire=int8_wire, input_crc=input_crc,
            )
            if self.impl.integrity is not None:
                _stamp_response_crc(self.impl, context, resp)
            return resp

        return await self._call("Predict", handler, request, context)

    async def Classify(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        return await self._call(
            "Classify",
            lambda req: self.impl.classify_async(
                req, deadline_s=deadline_s, criticality=crit
            ),
            request, context,
        )

    async def Regress(self, request, context):
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        return await self._call(
            "Regress",
            lambda req: self.impl.regress_async(
                req, deadline_s=deadline_s, criticality=crit
            ),
            request, context,
        )

    async def MultiInference(self, request, context):
        import asyncio

        # Off the event loop: multi_inference's sequential sub-calls BLOCK
        # on batcher futures (there is no *_async variant), and with
        # deadline propagation that stall window is client-controlled — one
        # MultiInference with a long deadline against a saturated batcher
        # must not freeze every other in-flight RPC.
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        entry_t = time.perf_counter()
        loop = asyncio.get_running_loop()

        def run(req, _fn=self.impl.multi_inference):
            overload_on = overload_mod.active()
            if overload_on:
                # Pool threads keep their contextvar context across uses:
                # drop any marker a FAILED earlier request left behind.
                overload_mod.consume_degraded()
            # Re-derive the REMAINING budget at executor start: time spent
            # queued behind other executor work belongs to the client's
            # budget, not on top of it.
            left = (
                None if deadline_s is None
                else deadline_s - (time.perf_counter() - entry_t)
            )
            resp = _fn(req, deadline_s=left, criticality=crit)
            # run_in_executor does NOT propagate contextvars back, so a
            # brownout stale-serve marker set in THIS thread must ride the
            # return value or the aio transport would mark stale results
            # fresh.
            return resp, (
                overload_mod.consume_degraded() if overload_on else None
            )

        async def dispatch(req):
            resp, degraded = await loop.run_in_executor(None, run, req)
            if degraded:
                overload_mod.mark_degraded(degraded)
            return resp

        return await self._call("MultiInference", dispatch, request, context)

    async def GetModelMetadata(self, request, context):
        return await self._call("GetModelMetadata", self.impl.get_model_metadata, request, context)

    async def PredictStream(self, request, context):
        """Server-streaming Predict on the coroutine server: an async
        generator awaiting each sub-batch completion on the event loop —
        same error mapping / metrics / tracing shape as _call, inlined
        because the stream must YIELD through the adapter."""
        t0 = time.perf_counter()
        ok = False
        model = _model_of(request)
        overload_on = overload_mod.active()
        if overload_on:
            overload_mod.consume_degraded()
        deadline_s = _deadline_of(context)
        crit = _criticality_of(context)
        chunk = _stream_chunk_of(context)
        if tracing.enabled():
            span_ctx = tracing.start_root(
                "server.PredictStream",
                traceparent=_traceparent_of(context),
                attrs={"entrypoint": "PredictStream",
                       **({"model": model} if model else {})},
            )
            try:
                await context.send_initial_metadata(
                    ((_PEER_ROLE_KEY, "replica"),)
                )
            except Exception:  # noqa: BLE001 — advisory only
                pass
        else:
            span_ctx = None
        try:
            agen = self.impl.predict_stream_async(
                request, deadline_s=deadline_s, criticality=crit, chunk=chunk
            )
            if span_ctx is not None:
                # Sync `with` across awaits: contextvars are coroutine-
                # scoped (the _call precedent).
                with span_ctx:
                    async for item in agen:
                        yield item
            else:
                async for item in agen:
                    yield item
            ok = True
            if overload_on:
                _push_overload_metadata(context, None)
        except ServiceError as e:
            if overload_on:
                _push_overload_metadata(context, e)
            await context.abort(_status(e.code), str(e))
        except grpc.aio.AbortError:
            raise
        except Exception as e:  # internal bug: surface as INTERNAL, keep serving
            log.exception("internal error serving PredictStream")
            await context.abort(grpc.StatusCode.INTERNAL, f"internal error: {e}")
        finally:
            self.metrics.observe(
                "PredictStream", time.perf_counter() - t0, ok, model=model
            )


class AioGrpcModelService(_AioServicerBase):
    """ModelService on the coroutine server: GetModelStatus is a cheap
    registry read and runs inline on the loop through the shared _call
    error mapping. Reload is inline ONLY for the label-flip mode; a
    multi-model lifecycle reload loads/warms whole models, which would
    stall every in-flight RPC on the single event-loop thread — it rides
    a worker thread instead (the lifecycle lock already serializes
    concurrent reloads, so off-loop dispatch adds no new interleaving)."""

    async def GetModelStatus(self, request, context):
        return await self._call("GetModelStatus", self.impl.get_model_status, request, context)

    async def HandleReloadConfigRequest(self, request, context):
        import asyncio

        fn = self.impl.handle_reload_config
        if self.impl.model_lifecycle is not None:
            loop = asyncio.get_running_loop()

            def dispatch(req, _fn=fn):
                # run_in_executor returns an awaitable future; _call awaits
                # it, keeping the loop free while the reload loads models.
                return loop.run_in_executor(None, _fn, req)

            fn = dispatch
        return await self._call("HandleReloadConfigRequest", fn, request, context)


def create_server_async(
    impl: PredictionServiceImpl,
    address: str = "127.0.0.1:0",
    metrics: ServerMetrics | None = None,
    uds_path: str | None = None,
) -> tuple["grpc.aio.Server", int]:
    """Build (not start) a grpc.aio server; returns (server, bound_port).
    Must be called from (or started on) the event loop that will own it.
    `uds_path` additionally binds a Unix-domain socket ([transport]
    uds_path) for co-located clients."""
    server = grpc.aio.server(
        options=list(LARGE_MESSAGE_CHANNEL_OPTIONS) + list(KEEPALIVE_SERVER_OPTIONS),
    )
    servicer = AioGrpcPredictionService(impl, metrics)
    add_PredictionServiceServicer_to_server(servicer, server)
    # Same port, second service — exactly tensorflow_model_server's layout.
    add_ModelServiceServicer_to_server(
        AioGrpcModelService(impl, servicer.metrics), server
    )
    # grpc.health.v1 on the coroutine server too (same status logic).
    add_HealthServicer_to_server(AioGrpcHealthService(impl), server)
    port = server.add_insecure_port(address)
    if port == 0:
        raise RuntimeError(f"could not bind {address}")
    if uds_path:
        _add_uds_port(server, uds_path)
    return server, port


def load_demo_servable(
    registry: ServableRegistry,
    kind: str = "dcn_v2",
    name: str = "DCN",
    version: int = 1,
    seed: int = 0,
    config: ModelConfig | None = None,
    **config_overrides,
) -> Servable:
    """Build + register a randomly-initialized servable (demo/bench path;
    production params come from train/checkpoint.py). An explicit `config`
    wins over keyword overrides."""
    config = config or ModelConfig(name=name, **config_overrides)
    model = build_model(kind, config)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    jax.block_until_ready(params)
    dense = config.num_dense_features if kind == "dlrm" else None
    servable = Servable(
        name=name,
        version=version,
        model=model,
        params=params,
        signatures=ctr_signatures(config.num_fields, with_dense=dense),
    )
    registry.load(servable)
    return servable


def start_rest_in_thread(impl, host: str, port: int, metrics=None) -> int:
    """Run the REST gateway (:8501 surface) on its own event loop in a
    daemon thread, next to a THREADED gRPC server — the gateway only
    touches the (thread-safe) impl/batcher. Startup is SYNCHRONIZED: an
    operator who asked for the surface gets a live port back or a
    RuntimeError, never a healthy-looking process with a dead thread
    (tensorflow_model_server exits on REST bind failure too; a wait()
    timeout counts as failure — the gateway state would be unknown).
    Shared by the single-host CLI and the multihost leader."""
    import asyncio
    import threading

    from .rest import start_rest_gateway

    rest_ready: dict = {}
    rest_up = threading.Event()

    def run_rest():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            _runner, bound = loop.run_until_complete(
                start_rest_gateway(impl, host, port, metrics)
            )
            rest_ready["port"] = bound
        except BaseException as exc:  # noqa: BLE001 — reported to caller
            rest_ready["error"] = exc
            return
        finally:
            rest_up.set()
        loop.run_forever()

    threading.Thread(target=run_rest, name="rest", daemon=True).start()
    if not rest_up.wait(timeout=30) or "error" in rest_ready:
        raise RuntimeError(
            f"REST gateway failed to start on {host}:{port}: "
            f"{rest_ready.get('error', 'startup timed out after 30s')}"
        )
    return rest_ready["port"]


def _replay_warmup(warmup_file, servable, batcher) -> int:
    from .warmup import replay_warmup_file

    return replay_warmup_file(warmup_file, servable, batcher)


def _servable_change_hook(score_cache, quality, row_cache=None):
    """ONE on_servable_change callable for the version watchers, fanning
    out to every armed plane that cares about registry mutations: the
    cache plane's generation invalidation (by model name) — BOTH tiers,
    the whole-request store and the row-granular store — and the quality
    plane's version-change accounting. The kernel plane needs no hook:
    its decision() is identity-guarded per tuned Servable (a hot-loaded
    or reloaded version can never inherit another generation's
    enablement, while the stable version keeps its measured win). None
    when nothing is armed, so the watcher keeps its no-hook fast path."""
    hooks = []
    if score_cache is not None:
        hooks.append(score_cache.invalidate_model)
    if row_cache is not None:
        hooks.append(row_cache.invalidate_model)
    if quality is not None:
        hooks.append(quality.note_servable_change)
    if not hooks:
        return None
    if len(hooks) == 1:
        return hooks[0]

    def hook(model_name: str) -> None:
        for h in hooks:
            h(model_name)

    return hook


class ModelLifecycle:
    """The model LIST as a runtime-reconcilable object (--model-config-file
    deployments): one version watcher per served model, plus `apply()` —
    the full HandleReloadConfigRequest semantics, where the supplied
    model_config_list REPLACES the model list (upstream behavior):

    - new entries start a watcher (whose synchronous first poll loads any
      ready version — the RPC returns with new models REGISTERED, like
      upstream's reload, which equally blocks on load);
    - entries absent from the new config stop their watcher and unload
      the model; an entry whose base_path or model_platform CHANGED is a
      remove+add (the watcher restarts on the new source);
    - unchanged entries get their version_labels applied DECLARATIVELY
      now (a label naming an unloaded version is FAILED_PRECONDITION;
      labels of restarted/new models seed via desired_labels as versions
      land).

    Reloads serialize on one lock — two concurrent conflicting reloads
    must not interleave — which also means a reload loading large models
    holds off shutdown until it completes (document-level trade-off,
    matching the blocking upstream RPC).

    build_stack returns it in the watcher slot (.stop() tears everything
    down, signalling all watchers before joining so drain time is the
    max, not the sum)."""

    def __init__(self, cfg, registry, batcher, model_config, mesh,
                 tensor_parallel: bool | None = None):
        import threading

        self._cfg = cfg
        self._registry = registry
        self._batcher = batcher
        self._model_config = model_config
        self._mesh = mesh
        # The EFFECTIVE layout knob: the [mesh] section's value when that
        # mode armed the mesh, cfg.tensor_parallel otherwise — watcher
        # loads must pre-place params in the layout the executor serves.
        self._tensor_parallel = (
            cfg.tensor_parallel if tensor_parallel is None else tensor_parallel
        )
        self._watchers: dict[str, object] = {}
        self._sources: dict[str, tuple[str, str]] = {}  # name -> (path, platform)
        self._lock = threading.Lock()  # reloads arrive on RPC threads

    @property
    def watchers(self):
        with self._lock:
            return list(self._watchers.values())

    def configured_models(self) -> set[str]:
        """Names this lifecycle owns a watcher for — configured, whether or
        not a version has landed yet (GetModelStatus reports START for the
        not-yet-ready ones instead of NOT_FOUND)."""
        with self._lock:
            return set(self._watchers)

    def _make_watcher(self, mc):
        from .version_watcher import VersionWatcher, VersionWatcherConfig

        cfg, batcher = self._cfg, self._batcher
        score_cache = getattr(batcher, "score_cache", None)
        row_cache = getattr(batcher, "row_cache", None)
        quality = getattr(batcher, "quality", None)
        kind = mc.model_platform or cfg.model_kind
        if kind == "tensorflow":  # upstream's only platform string
            kind = cfg.model_kind
        return VersionWatcher(
            mc.base_path,
            self._registry,
            VersionWatcherConfig(
                model_name=mc.name,
                model_kind=kind,
                desired_labels=tuple(
                    sorted((l, int(v)) for l, v in mc.version_labels.items())
                ),
                poll_interval_s=cfg.file_system_poll_wait_seconds,
                max_load_attempts=cfg.max_num_load_retries + 1,
            ),
            warmup=batcher.warmup_via_queue if cfg.warmup else None,
            warmup_replay=(
                (lambda sv, wf: _replay_warmup(wf, sv, batcher))
                if cfg.warmup else None
            ),
            model_config=self._model_config,
            mesh=self._mesh,
            tensor_parallel=self._tensor_parallel,
            # Version swaps drop the swapped model's cached scores the
            # moment the registry flips (cache-plane generation hook) and
            # tick the quality plane's version-change counter (ISSUE 7 —
            # version-pair drift reads the per-version sketches directly).
            on_servable_change=_servable_change_hook(
                score_cache, quality, row_cache=row_cache
            ),
        ).start()

    @staticmethod
    def _source_of(mc) -> tuple[str, str]:
        return (mc.base_path, mc.model_platform)

    def apply(self, model_configs) -> None:
        """Reconcile toward `model_configs` (validated entries). Raises
        registry label errors (ModelNotFound/VersionNotFound/ValueError)
        BEFORE mutating anything for the label changes it applies now."""
        with self._lock:
            wanted = {mc.name: mc for mc in model_configs}
            # An entry whose SOURCE changed is not "existing" — its
            # watcher must restart on the new base_path/platform
            # (upstream applies base-path moves on this same RPC).
            unchanged = {
                name for name in set(self._watchers) & set(wanted)
                if self._sources.get(name) == self._source_of(wanted[name])
            }
            # Declarative labels for UNCHANGED models: validate+apply
            # atomically first, so a bad label aborts the reload before
            # any watcher is started or stopped.
            existing_label_maps = {
                name: {l: int(v) for l, v in wanted[name].version_labels.items()}
                for name in unchanged
            }
            if existing_label_maps:
                self._registry.replace_label_maps(existing_label_maps)
            for name in sorted(set(self._watchers) - unchanged):
                w = self._watchers.pop(name)
                self._sources.pop(name, None)
                w.stop()
                try:
                    self._registry.unload(name)
                except KeyError:
                    pass  # never had a ready version
                log.info(
                    "reload: %s model %r",
                    "restarting" if name in wanted else "removed", name,
                )
            for name in sorted(set(wanted) - unchanged):
                self._watchers[name] = self._make_watcher(wanted[name])
                self._sources[name] = self._source_of(wanted[name])
                log.info("reload: added model %r (base_path=%s)",
                         name, wanted[name].base_path)

    def stop(self) -> None:
        with self._lock:
            watchers = list(self._watchers.values())
        for w in watchers:  # signal everyone first: drain in parallel
            w.request_stop()
        for w in watchers:
            w.stop()


def _parse_model_server_config(path):
    """Parse+validate a --model_config_file BEFORE any threads start, so a
    typo'd config fails with nothing to tear down. Returns the validated
    model_config_list entries."""
    import pathlib

    from google.protobuf import text_format

    from ..proto import serving_apis_pb2 as apis

    msc = text_format.Parse(
        pathlib.Path(path).read_text(), apis.ModelServerConfig()
    )
    if msc.WhichOneof("config") != "model_config_list" or not msc.model_config_list.config:
        raise ValueError(
            f"{path}: a model_config_list with at least one model is required"
        )
    from ..utils.config import validate_model_config_entries

    return validate_model_config_entries(msc.model_config_list.config, str(path))


def _start_model_config_watchers(
    cfg, model_configs, registry, batcher, model_config, mesh,
    tensor_parallel: bool | None = None,
):
    """tensorflow_model_server's --model_config_file: one version watcher
    per model_config_list entry — multi-model serving over ONE registry/
    batcher/impl (the registry keys servables by name, the batcher jit
    caches per servable, so nothing else changes shape).

    Upstream field mapping: `name` and `base_path` as-is; `model_platform`
    carries the zoo family here (upstream's "tensorflow" means "use the
    server's default family", since every model is a TF graph there);
    `version_labels` seed per-model label maps. Per-model ARCHITECTURE
    comes from each version's own artifact (native checkpoints carry a
    manifest; SavedModel dirs infer or use the global [model] section), so
    heterogeneous models need self-describing artifacts.
    """
    lifecycle = ModelLifecycle(
        cfg, registry, batcher, model_config, mesh,
        tensor_parallel=tensor_parallel,
    )
    lifecycle.apply(model_configs)
    return lifecycle


class GracefulShutdown:
    """Drain-aware teardown — ONE path for every way the server stops.

    SIGTERM (the deploy orchestrator's stop signal), REST-startup failure,
    and normal wait_for_termination exit all converge here, replacing the
    historical server.stop(0)-here / server.stop(2).wait()-there split.
    The sequence:

    1. `impl.draining = True`: the grpc.health.v1 servicer flips to
       NOT_SERVING (load balancers stop routing) and every NEW inference
       admission is refused UNAVAILABLE with a "draining" detail — fan-out
       clients reroute to another backend immediately.
    2. Version watchers stop (no new loads/warmups enter the batcher).
    3. `batcher.drain(grace_s)`: queued + staged + in-flight batches run
       to completion, bounded by the grace period — work the server
       ACCEPTED is work it answers.
    4. `server.stop(grace)` with the grace budget REMAINING after the
       drain (plus a small floor so handler threads can encode the
       responses the drain just completed), then batcher/request-log
       teardown.

    Idempotent and thread-safe: the first caller runs the sequence,
    everyone else (the SIGTERM thread racing the finally block, say)
    blocks until it finishes. `shutdown()` is safe from any thread;
    `install_signal_handler()` must run on the main thread."""

    # Floor for the post-drain RPC grace: even a fully-drained server
    # needs a beat for handler threads to serialize responses.
    MIN_RPC_GRACE_S = 1.0

    def __init__(
        self,
        impl,
        batcher,
        grace_s: float = 5.0,
        watcher=None,
        request_logger=None,
        lifecycle=None,
        recovery=None,
    ):
        self.impl = impl
        self.batcher = batcher
        self.grace_s = max(float(grace_s), 0.0)
        self.watcher = watcher
        self.request_logger = request_logger
        # Lifecycle controller (serving/lifecycle.py): stopped BEFORE the
        # watcher so a mid-drain tick can't publish/promote/rollback into
        # a stack that is tearing down.
        self.lifecycle = lifecycle
        # Recovery controller (serving/recovery.py): aborted BEFORE the
        # batcher drain — a SIGTERM arriving mid-REINIT must not leave
        # drain() waiting its whole grace on replayed batches the dying
        # replica will never finish (quarantine × shutdown interplay,
        # ISSUE 11 satellite). Captured-but-unreplayed items fail
        # UNAVAILABLE so their clients reroute immediately.
        self.recovery = recovery
        # Fleet plane (fleet/replica.py): announced IMMEDIATELY after the
        # draining flip — peers and the router hear the drain through
        # gossip before their next health probe — then stopped with the
        # transport.
        self.fleet = None
        self.server = None  # attached once created (create_server[_async])
        self.drained: bool | None = None
        self._lock = threading.Lock()
        self._started = False
        self._done = threading.Event()

    def install_signal_handler(self) -> bool:
        """Route SIGTERM through the drain sequence (main thread only —
        CPython restriction; embedded/test callers just call shutdown())."""
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:  # not the main thread
            return False
        return True

    def _on_sigterm(self, signum, frame) -> None:
        # Handlers run on the main thread, which is parked inside
        # wait_for_termination — the drain must run elsewhere so stop()
        # can unblock it.
        log.info("SIGTERM: draining (grace %.1fs)", self.grace_s)
        threading.Thread(
            target=self.shutdown, name="graceful-drain", daemon=True
        ).start()

    def shutdown(self) -> None:
        with self._lock:
            if self._started:
                run_it = False
            else:
                self._started = True
                run_it = True
        if not run_it:
            self._done.wait()
            return
        try:
            t0 = time.perf_counter()
            # 1. Refuse new work; health goes NOT_SERVING.
            self.impl.draining = True
            # 1.5. Tell the fleet NOW (one immediate push-pull round, not
            # the next interval): the router folds the draining record
            # into its scoreboard before this replica's first refused RPC.
            if self.fleet is not None:
                try:
                    self.fleet.announce()
                except Exception:
                    log.debug("fleet drain announce failed", exc_info=True)
            # 2. No new loads/warmups behind the drain: the lifecycle
            # controller first (its ticks drive the watcher), then the
            # watcher itself.
            if self.lifecycle is not None:
                self.lifecycle.stop()
            if self.watcher is not None:
                self.watcher.stop()
            cascade_watcher = getattr(self.impl, "cascade_watcher", None)
            if cascade_watcher is not None:
                cascade_watcher.stop()
            # 2.5. Abort any in-flight recovery cycle BEFORE the drain:
            # its watchdog stops, captured-but-unreplayed work fails
            # UNAVAILABLE (clients reroute — this replica is going away),
            # and drain() below can no longer deadlock waiting on a
            # replay that will never be issued.
            if self.recovery is not None:
                self.recovery.shutdown_for_drain(self.grace_s)
            # 3. Answer everything already accepted, bounded by grace.
            self.drained = self.batcher.drain(self.grace_s)
            if not self.drained:
                log.warning(
                    "drain grace %.1fs expired with work still in flight; "
                    "stopping anyway", self.grace_s,
                )
            # 4. Stop the transport with whatever grace remains (handlers
            # are unblocking off the just-completed batcher futures), then
            # the batcher and the log writer.
            left = max(
                self.grace_s - (time.perf_counter() - t0),
                self.MIN_RPC_GRACE_S,
            )
            if self.server is not None:
                self.server.stop(left).wait()
            if self.fleet is not None:
                self.fleet.stop()
            self.batcher.stop()
            if self.request_logger is not None:
                self.request_logger.close()
            log.info(
                "shutdown complete (drained=%s, %.1fs)",
                self.drained, time.perf_counter() - t0,
            )
        finally:
            self._done.set()


def build_stack(
    cfg: ServerConfig,
    checkpoint: str | None = None,
    savedmodel: str | None = None,
    model_config: ModelConfig | None = None,
    model_base_path: str | None = None,
    cache_config=None,
    overload_config=None,
    utilization_config=None,
    quality_config=None,
    lifecycle_config=None,
    batching_config=None,
    transport_config=None,
    recovery_config=None,
    kernels_config=None,
    mesh_config=None,
    elastic_config=None,
    cascade_config=None,
    integrity_config=None,
):
    """Registry + batcher (+ mesh executor) + impl from a ServerConfig.
    model_config (the TOML [model] section) pins the architecture for the
    demo and SavedModel-import paths; checkpoints carry their own.
    model_base_path switches to TF-Serving's versioned-directory lifecycle
    (serving/version_watcher.py) instead of a fixed artifact;
    cfg.model_config_file switches to MULTI-model serving (one watcher per
    model_config_list entry). cache_config (the TOML [cache] section, a
    utils.config.CacheConfig) arms the cache plane: an exact-match score
    cache + single-flight coalescing at submit, intra-batch dedup in the
    batcher, generation invalidation wired to every version watcher.
    overload_config (the TOML [overload] section, a utils.config.
    OverloadConfig) arms the adaptive overload plane: a self-tuning
    admission limit replaces the static queue_capacity_candidates bound,
    with criticality lanes, doomed-work refusal, brownout stale-serve
    (through the score cache, when armed), and retry-after pushback.
    utilization_config (the TOML [utilization] section, a utils.config.
    UtilizationConfig) arms the device-utilization attribution plane:
    an occupancy ledger + gap waterfall behind GET /utilz, the
    `utilization` block in /monitoring, dts_tpu_utilization_* Prometheus
    series, and a per-device counter track in the Chrome export.
    quality_config (the TOML [quality] section, a utils.config.
    QualityConfig) arms the model-quality plane: per-(model, version)
    score-distribution sketches fed from the batcher completer, PSI/JS
    drift vs a pinned reference and between live versions, the /labelz
    label-feedback join (windowed AUC + calibration), drift-linked trace
    exemplars, GET /qualityz, a `quality` block in /monitoring, and
    dts_tpu_quality_* Prometheus series.
    lifecycle_config (the TOML [lifecycle] section, a utils.config.
    LifecycleConfig) arms the continuous-freshness plane: canary
    admission over the version watcher's hot-swaps, drift/AUC
    auto-rollback with retire+blacklist, the optional fine-tune
    publisher, GET /lifecyclez, a `lifecycle` block in /monitoring, and
    dts_tpu_lifecycle_* Prometheus series — requires model_base_path
    (the watched dir IS the rollout mechanism) and an armed quality
    plane (the rollback signal).
    mesh_config (the TOML [mesh] section, a utils.config.MeshConfig)
    arms the MESH SERVING MODE (ISSUE 13): a ("data", "model") device
    mesh over the slice's chips with a hardened ShardedExecutor as the
    batcher's run_fn — candidate rows scattered over the data axis,
    embedding vocab over the model axis per the family's named partition
    rules, same wire protocol, one process spanning N chips. Mode
    conflicts are EXPLICIT build-time refusals, never runtime surprises:
    [kernels] (per-bucket kernel routing owns the single-chip
    executables), [recovery] scope='per_chip' (an SPMD executable spans
    every chip; whole-executor recovery COMPOSES — the mesh executor
    quarantines/reinits/replays as one unit), output_top_k (a
    single-chip jitted-entry variant), and the legacy [server]
    mesh_devices knob (pick one surface).
    elastic_config (the TOML [elastic] section, a utils.config.
    ElasticConfig; requires [mesh]) arms ELASTIC MESH SERVING
    (ISSUE 15): a pre-built, pre-warmed ladder of ("data", "model")
    splits over the same devices with a pressure/load-driven controller
    switching the serving split at runtime — hitlessly (new dispatches
    route to the target split while in-flight batches on the old split
    drain behind the per-split in-flight barrier; executables are
    warmup-compiled per rung, so a switch never compiles on the serving
    path). Surfaces: the `elastic` block in /meshz//monitoring and
    dts_tpu_elastic_* Prometheus series."""
    # Validate plane prerequisites BEFORE any threads exist — a typo'd
    # config must leave nothing to tear down.
    mesh_armed = mesh_config is not None and mesh_config.enabled
    if mesh_armed:
        if cfg.mesh_devices or cfg.model_parallel != 1 or cfg.tensor_parallel:
            raise ValueError(
                "[mesh] enabled conflicts with the legacy [server] mesh "
                "knobs (mesh_devices/model_parallel/tensor_parallel): "
                "configure the mesh in ONE place — the [mesh] section is "
                "the serving mode; drop the [server] copies"
            )
        if cfg.output_top_k:
            raise ValueError(
                "[mesh] enabled conflicts with output_top_k: top-k "
                "output compaction is a single-chip jitted-entry "
                "variant the sharded executor does not provide — "
                "disable one of them"
            )
        if (
            recovery_config is not None and recovery_config.enabled
            and getattr(recovery_config, "scope", "executor") == "per_chip"
        ):
            # The ISSUE-15 scoped lift: WHOLE-MESH recovery composes (the
            # watchdog treats the mesh executor as one unit — quarantine
            # captures everything, REINIT clears the executor's placed
            # params + sharded executables via clear_for_recovery, replay
            # re-dispatches through the re-warmed mesh). What stays
            # refused is the finer granularity nobody implements:
            raise ValueError(
                "[recovery] scope='per_chip' conflicts with [mesh]: an "
                "SPMD executable spans every chip of the mesh, so there "
                "is no per-chip quarantine to run — a sick chip takes "
                "the executor with it. Use scope='executor' (the "
                "default): the mesh executor quarantines, reinits, and "
                "replays as ONE unit"
            )
    lifecycle_armed = lifecycle_config is not None and lifecycle_config.enabled
    if lifecycle_armed:
        if not model_base_path:
            raise ValueError(
                "[lifecycle] enabled requires --model-base-path: the "
                "watched versioned dir is both the publish target and "
                "the hot-swap mechanism the canary/rollback loop drives"
            )
        if quality_config is None or not quality_config.enabled:
            raise ValueError(
                "[lifecycle] enabled requires [quality] enabled (or "
                "--quality): the rollback gate reads the quality plane's "
                "version-pair drift and per-version label AUC — a "
                "lifecycle with no signal could only ever promote blind"
            )
    elastic_armed = elastic_config is not None and elastic_config.enabled
    if elastic_armed and not mesh_armed:
        raise ValueError(
            "[elastic] enabled requires [mesh] enabled: the elastic "
            "plane re-factorizes the MESH's devices at runtime — the "
            "[mesh] section's split is where serving starts (and the "
            "ladder's rungs must factorize its device count). Arm both, "
            "or drop [elastic]"
        )
    integrity_armed = integrity_config is not None and integrity_config.enabled
    if (
        integrity_armed
        and integrity_config.shadow_fraction > 0
        and cache_config is not None
        and cache_config.enabled
    ):
        # Shadow verification's headline guarantee is "every delivered
        # score was (sampled-)verified bit-identical against a second
        # execution". Exact-match cache hits bypass the batcher entirely
        # — bytes inserted BEFORE the plane armed (or before a sick
        # period was detected) would be re-served for their whole TTL
        # with no detection layer ever touching them again. Refuse the
        # combination instead of silently weakening the guarantee; the
        # row cache and [kernels] COMPOSE (cold rows execute through the
        # shadow-eligible path, and both shadow executions route through
        # the same kernel-variant decision, so the compare stays within
        # the enabled variant).
        raise ValueError(
            "[integrity] shadow_fraction > 0 conflicts with [cache] "
            "enabled: exact-match cache hits re-serve cached score bytes "
            "without re-execution, so sampled shadow verification can "
            "never re-check them — the zero-corrupt-delivery guarantee "
            "would silently exclude every cache hit. Disable the score "
            "cache or set shadow_fraction = 0 (wire checksums and "
            "readback screens still compose with the cache)"
        )
    cascade_armed = cascade_config is not None and cascade_config.enabled
    if cascade_armed:
        if cfg.output_top_k:
            raise ValueError(
                "[cascade] enabled conflicts with output_top_k: the "
                "top-k wire replaces the score vector with (score, "
                "index) pairs, but the cascade's scatter needs the full "
                "vector to fill non-survivors from stage-1 scores — the "
                "two selections cannot both own the response shape. "
                "The cascade IS the retrieval-style compaction; drop "
                "output_top_k"
            )
        if mesh_armed:
            raise ValueError(
                "[cascade] enabled conflicts with [mesh] (and [elastic]):"
                " the stage-1 prune is a single-chip jitted-entry "
                "variant the sharded run_fn does not provide, so the "
                "cascade could only ever run its host fallback — "
                "disable one of them"
            )
    model_configs = None
    if cfg.model_config_file:
        if model_base_path or checkpoint or savedmodel:
            raise ValueError(
                "--model-config-file is mutually exclusive with "
                "--model-base-path/--checkpoint/--savedmodel (the config "
                "file owns the model list)"
            )
        if cfg.version_labels:
            raise ValueError(
                "--version-label / [server] version_labels have no meaning "
                "with --model-config-file; put per-model version_labels "
                "maps in the config file's model entries instead"
            )
        model_configs = _parse_model_server_config(cfg.model_config_file)
    registry = ServableRegistry()
    run_fn = None
    mesh = None
    tensor_parallel = cfg.tensor_parallel
    if mesh_armed:
        # First-class mesh serving mode (ISSUE 13): [mesh] / --mesh.
        from ..parallel import ShardedExecutor, make_mesh

        n_devices = mesh_config.devices or len(jax.devices())
        # The [mesh] section is AUTHORITATIVE for the layout (the legacy
        # [server] knobs were refused above, so no silent OR-merge).
        tensor_parallel = mesh_config.tensor_parallel
        if elastic_armed:
            # Elastic mesh serving (ISSUE 15): one ShardedExecutor per
            # ladder rung over the SAME devices, the [mesh] split as the
            # initial rung; warmup below pre-compiles every rung so a
            # runtime switch never pays a compile on the serving path.
            from ..parallel.elastic import (
                ElasticMeshExecutor,
                resolve_ladder,
            )

            if n_devices % mesh_config.model_parallel != 0:
                # Same refusal (and wording) make_mesh raises on the
                # static path — a typo'd [mesh] factorization must not
                # surface as a confusing ladder-entry error here.
                raise ValueError(
                    f"n_devices={n_devices} not divisible by "
                    f"model_parallel={mesh_config.model_parallel}"
                )
            initial = (
                n_devices // mesh_config.model_parallel,
                mesh_config.model_parallel,
            )
            ladder = resolve_ladder(elastic_config.splits, n_devices, initial)
            run_fn = ElasticMeshExecutor(
                splits=ladder,
                initial=initial,
                devices=list(jax.devices())[:n_devices],
                compress_transfer=cfg.compress_transfer,
                tensor_parallel=tensor_parallel,
                output_wire_dtype=cfg.output_wire_dtype,
                history_events=elastic_config.history_events,
            )
            mesh = run_fn.mesh
            log.info(
                "elastic mesh serving on: %d devices, ladder %s (initial "
                "%s) — `elastic` block in /meshz//monitoring, "
                "dts_tpu_elastic_* series",
                n_devices,
                [f"{d}x{m}" for d, m in ladder],
                f"{initial[0]}x{initial[1]}",
            )
        else:
            # make_mesh validates device availability and the
            # devices/model_parallel factorization (explicit refusals).
            mesh = make_mesh(
                n_devices, model_parallel=mesh_config.model_parallel
            )
            run_fn = ShardedExecutor(
                mesh,
                compress_transfer=cfg.compress_transfer,
                tensor_parallel=tensor_parallel,
                output_wire_dtype=cfg.output_wire_dtype,
            )
        log.info(
            "mesh serving mode on: %d devices as %s tensor_parallel=%s "
            "wire=%s — `mesh` block in /monitoring, dts_tpu_mesh_* series",
            n_devices, dict(mesh.shape), tensor_parallel,
            cfg.output_wire_dtype,
        )
    elif cfg.mesh_devices:
        # Legacy [server] mesh knobs (the dryrun/bench surface) — kept
        # working unchanged; production deployments use [mesh].
        from ..parallel import ShardedExecutor, make_mesh

        mesh = make_mesh(cfg.mesh_devices, model_parallel=cfg.model_parallel)
        run_fn = ShardedExecutor(
            mesh,
            compress_transfer=cfg.compress_transfer,
            tensor_parallel=cfg.tensor_parallel,
            output_wire_dtype=cfg.output_wire_dtype,
        )
    score_cache = cache_config.build() if cache_config is not None else None
    if score_cache is not None:
        log.info(
            "score cache on: max_entries=%d max_bytes=%d ttl_s=%.1f "
            "coalesce=%s dedup=%s — GET /cachez on the REST surface",
            cache_config.max_entries, cache_config.max_bytes,
            cache_config.ttl_s, cache_config.coalesce, cache_config.dedup,
        )
    row_cache = cache_config.build_row() if cache_config is not None else None
    if row_cache is not None:
        log.info(
            "row-granular score cache on: max_entries=%d max_bytes=%d "
            "ttl_s=%.1f coalesce=%s — only cold rows execute; `row_cache` "
            "block in /cachez and /monitoring",
            cache_config.row_max_entries, cache_config.row_max_bytes,
            cache_config.row_ttl_s, cache_config.row_coalesce,
        )
    utilization_ledger = (
        utilization_config.build() if utilization_config is not None else None
    )
    if utilization_ledger is not None:
        # Name the ledger's track after the real device (jax is already
        # initialized by this point on every build_stack path). Over a
        # mesh the ledger additionally attributes occupancy PER DEVICE:
        # SPMD batches occupy every chip of the mesh simultaneously, so
        # each device carries the busy timeline (snapshot per_device +
        # one Perfetto counter track per chip).
        try:
            if mesh is not None:
                utilization_ledger.devices = [
                    str(d) for d in mesh.devices.flat
                ]
                utilization_ledger.device = (
                    f"mesh{dict(mesh.shape)}"
                )
            else:
                utilization_ledger.device = str(jax.devices()[0])
        except Exception:  # noqa: BLE001 — a label, never a dependency
            pass
        log.info(
            "utilization attribution on: ring=%d window_s=%.1f "
            "calibrated=%s — GET /utilz on the REST surface",
            utilization_config.ring, utilization_config.window_seconds,
            bool(utilization_config.calibration_file),
        )
    quality_monitor = (
        quality_config.build() if quality_config is not None else None
    )
    if quality_monitor is not None:
        log.info(
            "model-quality observability on: bins=%d window_s=%.1f "
            "drift_threshold_psi=%.2f reference_file=%s — GET /qualityz "
            "and POST /labelz on the REST surface",
            quality_config.bins, quality_config.window_seconds,
            quality_config.drift_threshold_psi,
            quality_config.reference_file or "<none>",
        )
    from ..utils.config import KernelsConfig as _KernelsConfig

    # build() with a disabled (or absent) section DISARMS the module-level
    # int8 score-wire gate — a stack built without the plane must never
    # inherit a previous stack's armed wire in the same process.
    kernel_manager = (kernels_config or _KernelsConfig()).build()
    if kernel_manager is not None:
        if cfg.mesh_devices or mesh_armed:
            raise ValueError(
                "[kernels] enabled requires the single-chip batcher path: "
                "the ShardedExecutor mirrors the int8 output wire but owns "
                "its own executables (per-bucket kernel routing over a "
                "mesh is future work) — disable [kernels] or [mesh]"
            )
        log.info(
            "kernel plane on: quantize=%s pallas=%s autotune=%s "
            "measure_only=%s gates(speedup>=%.2f |dScore|<=%.4f "
            "|dAUC|<=%.4f) int8_score_wire=%s table=%s",
            kernels_config.quantize, kernels_config.pallas,
            kernels_config.autotune, kernels_config.measure_only,
            kernels_config.min_speedup, kernels_config.max_abs_delta,
            kernels_config.auc_margin, kernels_config.int8_score_wire,
            kernels_config.table_file or "<none>",
        )
    overload_ctrl = (
        overload_config.build() if overload_config is not None else None
    )
    if overload_ctrl is not None:
        log.info(
            "adaptive overload control on: target_queue_wait_ms=%.1f "
            "brownout_after=%d shed_after=%d stale_while_overloaded_s=%.1f "
            "— `overload` block in /monitoring",
            overload_config.target_queue_wait_ms,
            overload_config.brownout_after_intervals,
            overload_config.shed_after_intervals,
            overload_config.stale_while_overloaded_s,
        )
    # Continuous-batching pipeline knobs ([batching], ISSUE 9): the
    # section's pipeline_depth (when nonzero) wins over the legacy
    # [server] location; the in-flight window / buffer ring / stream
    # split live only in the section and default off.
    pipeline_depth = cfg.pipeline_depth
    inflight_window = 0
    buffer_ring = False
    if batching_config is not None:
        pipeline_depth = batching_config.pipeline_depth or pipeline_depth
        inflight_window = batching_config.inflight_window
        buffer_ring = batching_config.buffer_ring
        if inflight_window or buffer_ring or batching_config.pipeline_depth:
            log.info(
                "continuous-batching pipeline: depth=%d inflight_window=%s "
                "buffer_ring=%s stream_chunk=%d",
                pipeline_depth, inflight_window or "unbounded", buffer_ring,
                batching_config.stream_chunk_candidates,
            )
    batcher = DynamicBatcher(
        buckets=cfg.buckets,
        max_wait_us=cfg.max_wait_us,
        compress_transfer=cfg.compress_transfer,
        run_fn=run_fn,
        pipeline_depth=pipeline_depth,
        inflight_window=inflight_window,
        buffer_ring=buffer_ring,
        queue_capacity_candidates=cfg.queue_capacity_candidates,
        completion_workers=cfg.completion_workers,
        output_wire_dtype=cfg.output_wire_dtype,
        output_top_k=cfg.output_top_k,
        async_readback=cfg.async_readback,
        pipelined_dispatch=cfg.pipelined_dispatch,
        donate_buffers=cfg.donate_buffers,
        score_cache=score_cache,
        row_cache=row_cache,
        # `enabled` is the MASTER switch for the whole cache plane: a
        # config with enabled=false and dedup=true must arm nothing.
        dedup=(
            cache_config.enabled and cache_config.dedup
            if cache_config is not None else False
        ),
        overload=overload_ctrl,
        utilization=utilization_ledger,
        quality=quality_monitor,
    ).start()
    impl = PredictionServiceImpl(registry, batcher)
    if run_fn is not None and hasattr(run_fn, "snapshot"):
        # Mesh serving surface: /monitoring's `mesh` block and the
        # dts_tpu_mesh_* Prometheus series read the executor's snapshot
        # (geometry, per-device list, pad/batch counters, layout source)
        # — wired for the legacy mesh knobs too, so the dryrun/bench
        # surface reports identically.
        impl.mesh_executor = run_fn
    if elastic_armed:
        # Elastic controller (ISSUE 15): pressure (overload state, when
        # that plane is armed) + the batcher's queue-load/bucket-occupancy
        # EWMA drive runtime split switches. No thread — ticks ride the
        # dispatch path and monitoring scrapes (the overload precedent).
        from ..parallel.elastic import ElasticController

        impl.elastic = ElasticController(
            elastic_config,
            run_fn,
            overload=overload_ctrl,
            load_fn=batcher.queue_load,
            largest_bucket=max(cfg.buckets),
        )
        log.info(
            "elastic controller on: tick=%.2fs dwell=%.1fs up/down after "
            "%d/%d ticks, load thresholds %.2f/%.2f, overload pressure "
            "%s",
            elastic_config.tick_interval_s, elastic_config.dwell_s,
            elastic_config.up_after_ticks, elastic_config.down_after_ticks,
            elastic_config.load_up_threshold,
            elastic_config.load_down_threshold,
            "wired" if overload_ctrl is not None else "absent (load-only)",
        )
    if kernel_manager is not None:
        # Attach the kernel plane: the batcher consults the per-bucket
        # decision table at dispatch; /monitoring + Prometheus read
        # impl.kernels. Decisions stay empty (= baseline) until the
        # autotune below (or a persisted-table adoption) fills them.
        batcher.kernels = kernel_manager
        impl.kernels = kernel_manager

    def _prepare_kernels(sv) -> None:
        # Autotune at load time — the compile-storms-belong-at-warmup
        # rule applies to variant measurement too. A persisted table for
        # this exact (model, version, device, gates) is adopted without
        # re-measuring; measure_only records without enabling.
        if kernel_manager is None or sv is None:
            return
        try:
            kernel_manager.prepare(batcher, sv)
        except Exception:  # noqa: BLE001 — a failed tune means baseline
            log.exception("kernel autotune failed; serving the baseline")

    if batching_config is not None:
        # Streamed sub-batch default ([batching] stream_chunk_candidates;
        # a request's x-dts-stream-chunk metadata overrides per call).
        impl.stream_chunk_candidates = batching_config.stream_chunk_candidates
    if transport_config is not None and transport_config.response_arena:
        # Reusable response-encode scratch ([transport] response_arena).
        impl.response_arena = True
        log.info("response-encode arenas on ([transport] response_arena)")
    if recovery_config is not None and recovery_config.enabled:
        # Device-failure recovery plane (serving/recovery.py): attaches
        # itself as batcher.recovery; impl.recovery drives the health
        # flip and /recoveryz. The watchdog thread starts in serve() —
        # embedded callers drive check()/run_cycle() themselves.
        from .recovery import RecoveryController

        impl.recovery = RecoveryController(
            recovery_config, batcher, registry=registry, impl=impl
        )
        log.info(
            "device-failure recovery on: wedge_quarantine_s=%.1f "
            "replay_budget=%d poison_kills=%d — GET /recoveryz on the "
            "REST surface",
            recovery_config.wedge_quarantine_s,
            recovery_config.replay_budget, recovery_config.poison_kills,
        )
    if integrity_armed:
        # Data-integrity plane (serving/integrity.py, ISSUE 20): ONE
        # plane object shared by every hook site — the batcher (shadow
        # sampling + readback screens + escalation), the impl (input CRC
        # verify, response stamping, /integrityz), and the transports
        # (metadata read/write) all reach the same counters.
        integrity_plane = integrity_config.build()
        batcher.integrity = integrity_plane
        impl.integrity = integrity_plane
        log.info(
            "data-integrity plane on: wire_checksums=%s screen=%s "
            "shadow_fraction=%.3f trips/window=%d/%.1fs — GET /integrityz "
            "on the REST surface",
            integrity_config.wire_checksums, integrity_config.screen,
            integrity_config.shadow_fraction,
            integrity_config.screen_trips_per_window,
            integrity_config.screen_window_s,
        )
    # Health gating: the grpc.health.v1 servicer reports the overall server
    # NOT_SERVING until the load+warmup phase below completes (standard
    # probes and the client's half-open probing key off this).
    impl.warmup_complete = False

    if cascade_armed:
        # Multi-stage ranking cascade (serving/cascade.py, ISSUE 19): the
        # first-stage servable is a NORMAL registry entry under its own
        # model name — published/hot-swapped through the same versioned-
        # dir machinery as any other model when stage1_base_path is set,
        # else built in-process from the primary architecture (towers
        # share the feature layout; two_tower's user/item split must stay
        # a real split).
        from .cascade import CascadeOrchestrator

        base_mc = model_config or ModelConfig(
            name=cfg.model_name, num_fields=cfg.num_fields
        )
        s1_overrides = {"name": cascade_config.stage1_model}
        if (
            cascade_config.stage1_kind == "two_tower"
            and base_mc.num_user_fields >= base_mc.num_fields
        ):
            s1_overrides["num_user_fields"] = max(1, base_mc.num_fields // 2)
        stage1_mc = dataclasses.replace(base_mc, **s1_overrides)
        if cascade_config.stage1_base_path:
            from .version_watcher import VersionWatcher, VersionWatcherConfig

            impl.cascade_watcher = VersionWatcher(
                cascade_config.stage1_base_path,
                registry,
                VersionWatcherConfig(
                    model_name=cascade_config.stage1_model,
                    model_kind=cascade_config.stage1_kind,
                    poll_interval_s=cfg.file_system_poll_wait_seconds,
                    max_load_attempts=cfg.max_num_load_retries + 1,
                ),
                warmup=batcher.warmup_via_queue if cfg.warmup else None,
                model_config=stage1_mc,
                on_servable_change=_servable_change_hook(
                    score_cache, quality_monitor, row_cache=row_cache
                ),
            ).start()
        else:
            stage1_sv = load_demo_servable(
                registry,
                kind=cascade_config.stage1_kind,
                name=cascade_config.stage1_model,
                config=stage1_mc,
            )
            if cfg.warmup:
                batcher.warmup(stage1_sv)
        impl.cascade = CascadeOrchestrator(
            registry, batcher,
            stage1_model=cascade_config.stage1_model,
            survivor_k=cascade_config.survivor_k,
            survivor_fraction=cascade_config.survivor_fraction,
            score_threshold=cascade_config.score_threshold,
            min_candidates=cascade_config.min_candidates,
        )
        log.info(
            "cascade on: stage1=%s (%s%s) survivors=%s threshold=%s "
            "min_candidates=%d — GET /cascadez on the REST surface",
            cascade_config.stage1_model, cascade_config.stage1_kind,
            f" from {cascade_config.stage1_base_path}"
            if cascade_config.stage1_base_path else " demo",
            cascade_config.survivor_k or
            f"{cascade_config.survivor_fraction:.0%}",
            cascade_config.score_threshold or "<off>",
            cascade_config.min_candidates,
        )

    if model_configs is not None:
        watchers = _start_model_config_watchers(
            cfg, model_configs, registry, batcher, model_config, mesh,
            tensor_parallel=tensor_parallel,
        )
        # Runtime model-list reloads (HandleReloadConfigRequest) reconcile
        # through the same lifecycle object.
        impl.model_lifecycle = watchers
        served = registry.models()
        if served:
            log.info("serving %d model(s) from %s: %s",
                     len(served), cfg.model_config_file,
                     {k: v for k, v in sorted(served.items())})
        else:
            log.warning("no ready versions for any configured model yet; watching")
        # Representative servable for the startup banner: the configured
        # default name when it is served, else any ready model — 'awaiting
        # versions' must mean NOTHING is ready, not 'DCN isn't configured'.
        ready = cfg.model_name if cfg.model_name in served else (
            sorted(served)[0] if served else None
        )
        servable = registry.resolve(ready) if ready else None
        impl.warmup_complete = True
        return registry, batcher, impl, servable, mesh, watchers
    if model_base_path:
        if checkpoint or savedmodel:
            raise ValueError(
                "--model-base-path is mutually exclusive with "
                "--checkpoint/--savedmodel (the base path owns version lifecycle)"
            )
        from .version_watcher import VersionWatcher, VersionWatcherConfig

        watcher = VersionWatcher(
            model_base_path,
            registry,
            VersionWatcherConfig(
                model_name=cfg.model_name,
                model_kind=cfg.model_kind,
                desired_labels=cfg.version_labels,
                poll_interval_s=cfg.file_system_poll_wait_seconds,
                # Upstream semantics: N RETRIES after the first attempt,
                # so total attempts = N + 1.
                max_load_attempts=cfg.max_num_load_retries + 1,
            ),
            # warmup_via_queue: compilation rides the batching thread, so a
            # hot-load never races the jit caches with live traffic.
            warmup=batcher.warmup_via_queue if cfg.warmup else None,
            warmup_replay=(
                (lambda sv, wf: _replay_warmup(wf, sv, batcher))
                if cfg.warmup else None
            ),
            model_config=model_config
            or ModelConfig(name=cfg.model_name, num_fields=cfg.num_fields),
            mesh=mesh,
            tensor_parallel=tensor_parallel,
            on_servable_change=_servable_change_hook(
                score_cache, quality_monitor, row_cache=row_cache
            ),
        ).start()
        # Label-only reloads may re-state this source verbatim (deploy
        # tools replay full configs); anything ELSE is a rejected move.
        impl.served_sources[cfg.model_name] = (str(model_base_path), cfg.model_kind)
        impl.version_watcher = watcher
        if lifecycle_armed:
            from .lifecycle import LifecycleController

            impl.lifecycle = LifecycleController(
                lifecycle_config,
                registry=registry,
                model_name=cfg.model_name,
                watcher=watcher,
                quality=quality_monitor,
            )
            log.info(
                "continuous-freshness lifecycle on: probe_only=%.1fs "
                "ramp %.2f+%.2f/%.1fs to %.2f, promote_after=%.1fs, "
                "rollback psi>=%.2f auc_drop>=%.3f, fine_tune every %s — "
                "GET /lifecyclez on the REST surface",
                lifecycle_config.canary_probe_only_s,
                lifecycle_config.canary_initial_fraction,
                lifecycle_config.canary_ramp_step,
                lifecycle_config.canary_step_dwell_s,
                lifecycle_config.canary_max_fraction,
                lifecycle_config.promote_after_s,
                lifecycle_config.rollback_psi,
                lifecycle_config.rollback_auc_drop,
                (f"{lifecycle_config.fine_tune_interval_s:.0f}s"
                 if lifecycle_config.fine_tune_interval_s > 0 else "<off>"),
            )
        versions = registry.models().get(cfg.model_name, [])
        if not versions:
            log.warning("no ready versions under %s yet; watching", model_base_path)
            servable = None
        else:
            servable = registry.resolve(cfg.model_name)
            log.info("serving %s versions %s from %s", cfg.model_name, versions, model_base_path)
        _prepare_kernels(servable)
        impl.warmup_complete = True
        return registry, batcher, impl, servable, mesh, watcher
    if savedmodel:
        from ..interop import import_savedmodel
        from .warmup import warmup_file_for

        servable = import_savedmodel(
            savedmodel,
            cfg.model_kind,
            model_config
            or ModelConfig(name=cfg.model_name, num_fields=cfg.num_fields),
            name=cfg.model_name,
        )
        wf = warmup_file_for(savedmodel)
        if wf is not None and cfg.warmup:
            n = _replay_warmup(wf, servable, batcher)
            log.info("replayed %d warmup records from %s", n, wf)
        registry.load(servable)
        log.info("imported SavedModel %s: %s v%d", savedmodel, servable.name, servable.version)
    elif checkpoint:
        from ..train.checkpoint import load_servable

        servable = load_servable(checkpoint, mesh=mesh, tensor_parallel=tensor_parallel)
        registry.load(servable)
        log.info("loaded checkpoint %s: %s v%d", checkpoint, servable.name, servable.version)
    else:
        servable = load_demo_servable(
            registry,
            kind=cfg.model_kind,
            name=cfg.model_name,
            config=model_config,
            num_fields=cfg.num_fields,
        )
    if cfg.warmup:
        log.info("warming bucket ladder %s", cfg.buckets)
        batcher.warmup(servable)
    # Static-artifact paths load exactly the versions above, so a label
    # naming anything else is a config error — fail at startup, like
    # tensorflow_model_server refusing labels on unavailable versions
    # (the watcher path instead retries as versions land).
    for label, version in cfg.version_labels:
        registry.set_label(cfg.model_name, label, version)
        log.info("label %r -> %s v%d", label, cfg.model_name, version)
    _prepare_kernels(servable)
    impl.warmup_complete = True
    return registry, batcher, impl, servable, mesh, None


def serve(argv=None) -> None:
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Honor an explicit CPU request over this image's sitecustomize
        # axon pin (config-level override required before backend init —
        # same guard as bench.py's probe and interop/export.py).
        jax.config.update("jax_platforms", "cpu")
    parser = argparse.ArgumentParser(description="TPU-native PredictionService")
    parser.add_argument("--config", help="TOML config file ([server] section)")
    parser.add_argument("--checkpoint", help="servable checkpoint dir (train.save_servable)")
    parser.add_argument(
        "--savedmodel",
        help="TF SavedModel dir to import and serve (interop/savedmodel.py; "
        "model family/config from --model-kind/--num-fields)",
    )
    parser.add_argument(
        "--model-base-path", dest="model_base_path",
        help="TF-Serving-style versioned base dir (<base>/1/, <base>/2/, ...): "
        "hot-loads new versions, retires old ones (serving/version_watcher.py)",
    )
    parser.add_argument("--port", type=int)
    parser.add_argument("--host")
    parser.add_argument("--model-kind", dest="model_kind")
    parser.add_argument("--model-name", dest="model_name")
    parser.add_argument("--num-fields", dest="num_fields", type=int)
    parser.add_argument("--max-workers", dest="max_workers", type=int)
    parser.add_argument("--max-wait-us", dest="max_wait_us", type=int)
    parser.add_argument(
        "--mesh", action="store_true", default=None,
        help="mesh serving mode (ISSUE 13): shard serving over a "
        "('data', 'model') device mesh — candidate rows over the data "
        "axis, embedding vocab over the model axis, one process "
        "spanning N chips behind the same wire protocol. Equivalent to "
        "[mesh] enabled=true; with --mesh, --mesh-devices / "
        "--model-parallel / --tensor-parallel configure the MESH "
        "section (`mesh` block in /monitoring, dts_tpu_mesh_* series). "
        "Refuses [kernels], [recovery] scope='per_chip', and "
        "output_top_k at build time; whole-executor [recovery] and "
        "[elastic] compose",
    )
    parser.add_argument(
        "--elastic", action="store_true", default=None,
        help="elastic mesh serving (ISSUE 15; requires --mesh or [mesh]): "
        "pre-build a ladder of ('data', 'model') splits over the same "
        "devices and let a pressure/load-driven controller switch the "
        "serving split at runtime — hitlessly, with warmup-compiled "
        "executables per rung. Equivalent to [elastic] enabled=true "
        "(`elastic` block in /meshz//monitoring, dts_tpu_elastic_* "
        "series)",
    )
    parser.add_argument(
        "--cascade", action="store_true", default=None,
        help="in-server multi-stage ranking cascade (ISSUE 19): score the "
        "full candidate batch with a cheap first-stage servable (its own "
        "registry entry — hot-swappable like any model), take the top "
        "survivors ON DEVICE so only survivor rows cross the wire-dtype "
        "D2H, then rank just the survivors with the primary model; "
        "non-survivors keep their stage-1 scores and every row carries "
        "stage provenance in the response. Equivalent to [cascade] "
        "enabled=true (`cascade` block in /monitoring, GET /cascadez, "
        "dts_tpu_cascade_* series). Refuses output_top_k and [mesh]/"
        "[elastic] at build time",
    )
    parser.add_argument("--mesh-devices", dest="mesh_devices", type=int)
    parser.add_argument("--model-parallel", dest="model_parallel", type=int)
    parser.add_argument(
        "--tensor-parallel", dest="tensor_parallel", action="store_true", default=None,
        help="shard dense MLP/cross weights over the model axis",
    )
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument("--rest-port", dest="rest_port", type=int, default=0,
                        help="also serve the TF-Serving REST API (:8501 "
                        "surface, /v1/models/... routes) on this port")
    parser.add_argument("--metrics-every-s", type=float, default=0.0,
                        help="periodically log a metrics snapshot")
    parser.add_argument(
        "--tracing", action="store_true", default=None,
        help="per-request span tracing (W3C traceparent propagation; GET "
        "/tracez on the REST surface, ?format=chrome for a Perfetto-"
        "loadable export). Equivalent to [observability] tracing=true",
    )
    parser.add_argument(
        "--cache", action="store_true", default=None,
        help="exact-match score cache + single-flight coalescing at the "
        "batcher (cache/score_cache.py; GET /cachez on the REST surface). "
        "Equivalent to [cache] enabled=true; the [cache] section carries "
        "the capacity/ttl/coalesce/dedup knobs and the row-granular tier "
        "(row_granular: per-row score caching — only cold rows execute)",
    )
    parser.add_argument(
        "--overload", action="store_true", default=None,
        help="adaptive overload control (serving/overload.py): self-tuning "
        "admission limit driven by queue-wait vs target, criticality "
        "lanes, doomed-work refusal, brownout stale-serve, retry-after "
        "pushback. Equivalent to [overload] enabled=true; the [overload] "
        "section carries the target/limit/brownout/stale knobs",
    )
    parser.add_argument(
        "--utilization", action="store_true", default=None,
        help="device-utilization attribution (serving/utilization.py): "
        "occupancy ledger + idle-gap waterfall (GET /utilz on the REST "
        "surface, `utilization` block in /monitoring, "
        "dts_tpu_utilization_* Prometheus series, Perfetto counter "
        "track) with a live achieved_fraction_of_device_limit estimate. "
        "Equivalent to [utilization] enabled=true; the [utilization] "
        "section carries the ring/window/calibration knobs",
    )
    parser.add_argument(
        "--quality", action="store_true", default=None,
        help="model-quality observability (serving/quality.py): "
        "per-(model, version) score-distribution sketches fed from the "
        "batcher completer, PSI/JS drift vs a pinned reference "
        "(POST /qualityz/snapshot) and between live versions, label "
        "feedback via POST /labelz (windowed AUC + calibration), and "
        "drift-linked /tracez exemplars (GET /qualityz, `quality` block "
        "in /monitoring, dts_tpu_quality_* Prometheus series). "
        "Equivalent to [quality] enabled=true; the [quality] section "
        "carries the bins/window/drift/label knobs",
    )
    parser.add_argument(
        "--lifecycle", action="store_true", default=None,
        help="continuous-freshness lifecycle (serving/lifecycle.py): "
        "canary admission over the version watcher's hot-swaps (probe "
        "lane first, then a configurable default-lane ramp), drift/AUC "
        "auto-rollback with retire+blacklist, and the optional "
        "fine-tune publisher ([lifecycle] fine_tune_interval_s). "
        "Requires --model-base-path and --quality (the rollback "
        "signal). Equivalent to [lifecycle] enabled=true; the "
        "[lifecycle] section carries the ramp/threshold/publisher knobs "
        "(GET /lifecyclez, `lifecycle` block in /monitoring, "
        "dts_tpu_lifecycle_* Prometheus series)",
    )
    parser.add_argument(
        "--recovery", action="store_true", default=None,
        help="device-failure recovery plane (serving/recovery.py): a "
        "watchdog escalates the batcher's wedge clock into a "
        "quarantine (health NOT_SERVING, new work refused UNAVAILABLE "
        "so clients failover), tears down and rebuilds the jitted "
        "executors in-process, replays every in-flight and queued "
        "request, and bisects a batch that deterministically kills the "
        "executor to isolate poisoned inputs (they alone fail "
        "INVALID_ARGUMENT). Equivalent to [recovery] enabled=true; the "
        "[recovery] section carries the watchdog/replay/bisection knobs "
        "(GET /recoveryz, `recovery` block in /monitoring, "
        "dts_tpu_recovery_* Prometheus series)",
    )
    parser.add_argument(
        "--kernels", action="store_true", default=None,
        help="kernel/quantization plane (ops/quantize.py + ops/autotune.py"
        " + the fused Pallas serving kernel): post-training int8 weight "
        "quantization and the fused gather+cross+MLP kernel, each enabled "
        "PER BUCKET only where the warmup autotune harness measured a "
        "speedup > 1 on this device AND the accuracy gates passed "
        "(max |dScore| bound; AUC margin when a labeled eval is supplied)."
        " Equivalent to [kernels] enabled=true; the [kernels] section "
        "carries the gate/table knobs (`kernels` block in /monitoring, "
        "dts_tpu_kernel_* Prometheus series)",
    )
    parser.add_argument(
        "--fleet", action="store_true", default=None,
        help="fleet robustness plane (fleet/): join the cross-replica "
        "health gossip mesh and follow fleet-coordinated rollout state "
        "(fleet/gossip.py + fleet/rollout.py). Equivalent to [fleet] "
        "enabled=true; the [fleet] section carries the self_id/peers/"
        "gossip/rollout knobs (GET /fleetz, `fleet` block in /monitoring, "
        "dts_tpu_fleet_* Prometheus series)",
    )
    parser.add_argument(
        "--integrity", action="store_true", default=None,
        help="end-to-end data-integrity plane (serving/integrity.py): "
        "CRC32C wire checksums over tensor bytes both directions "
        "(x-dts-input-crc verified at decode — a corrupted request fails "
        "alone, never its batch; x-dts-score-crc stamped on responses "
        "for opted-in clients), post-readback NaN/Inf sanity screens "
        "that fail only the corrupted row, and sampled bit-identity "
        "shadow re-execution whose mismatches escalate into the "
        "[recovery] quarantine->reinit->replay cycle and gossip a "
        "`suspect` verdict fleet-wide. Equivalent to [integrity] "
        "enabled=true; the [integrity] section carries the "
        "screen/shadow knobs (GET /integrityz, POST /integrityz/audit, "
        "`integrity` block in /monitoring, dts_tpu_integrity_* "
        "Prometheus series)",
    )
    parser.add_argument(
        "--router", action="store_true", default=None,
        help="run as the FLEET ROUTER instead of a serving replica "
        "(fleet/router.py): a jax-free tier speaking the PredictionService "
        "wire protocol that embeds the sharded fan-out client as its "
        "steering brain — fleet-scope row affinity, hedging, failover, "
        "gossip-informed scoreboard, single-writer rollout coordination. "
        "Requires --config with [client] hosts (the replica fleet) and "
        "[fleet]; ignores every serving/model flag",
    )
    parser.add_argument(
        "--uds-path", dest="uds_path",
        help="also serve gRPC on this Unix-domain socket path (co-located "
        "fan-out clients dial unix:<path>, skipping the TCP/loopback "
        "stack). Equivalent to [transport] uds_path",
    )
    parser.add_argument(
        "--stream-chunk", dest="stream_chunk", type=int,
        help="default candidates per PredictStream sub-batch (server-side "
        "split; 0 = single chunk). Equivalent to [batching] "
        "stream_chunk_candidates; requests override via "
        "x-dts-stream-chunk metadata",
    )
    parser.add_argument(
        "--batching-parameters-file", dest="batching_parameters_file",
        help="tensorflow_model_server-format batching config (text-format "
        "BatchingParameters): allowed_batch_sizes -> bucket ladder, "
        "batch_timeout_micros -> max_wait_us, etc. (utils/config.py "
        "apply_batching_parameters); applied over [server] TOML values",
    )
    parser.add_argument(
        "--model-config-file", dest="model_config_file",
        help="multi-model serving: a tensorflow_model_server-format "
        "ModelServerConfig textproto (model_config_list of name/base_path/"
        "model_platform/version_labels; one version watcher per model)",
    )
    parser.add_argument(
        "--file-system-poll-wait-seconds", dest="file_system_poll_wait_seconds",
        type=float, help="version-watcher poll interval (upstream flag name)",
    )
    parser.add_argument(
        "--max-num-load-retries", dest="max_num_load_retries", type=int,
        help="bounded retries for a failing version load (upstream flag name)",
    )
    parser.add_argument(
        "--ssl-config-file", dest="ssl_config_file",
        help="serve gRPC over TLS: a tensorflow_model_server-format "
        "SSLConfig textproto (PEM contents inline; client_verify=true "
        "for mTLS) — load_ssl_credentials",
    )
    parser.add_argument(
        "--request-log-file", dest="request_log_file",
        help="log a sample of requests as PredictionLog TFRecords (the "
        "upstream LoggingConfig surface; output is directly usable as an "
        "assets.extra/tf_serving_warmup_requests file)",
    )
    parser.add_argument(
        "--request-log-sampling", dest="request_log_sampling", type=float,
        help="sampling rate in [0,1] for --request-log-file (default 0.01)",
    )
    parser.add_argument(
        "--version-label", dest="version_label_args", action="append",
        metavar="LABEL=VERSION", default=None,
        help="assign a version label (repeatable), e.g. --version-label "
        "stable=2 --version-label canary=3; requests may then address "
        "/labels/{label} (REST) or ModelSpec.version_label (gRPC)",
    )
    args = parser.parse_args(argv)

    if args.router:
        # Router tier: no model, no jax, no batcher — delegate to the
        # fleet router's own entry point before any stack build. Shared
        # transport flags pass through; everything else is replica-only.
        if not args.config:
            raise SystemExit("--router requires --config ([client] hosts "
                             "+ [fleet] section)")
        from ..fleet.router import main as router_main

        router_argv = ["--config", args.config]
        if args.host:
            router_argv += ["--host", args.host]
        if args.port:
            router_argv += ["--port", str(args.port)]
        if args.uds_path:
            router_argv += ["--uds-path", args.uds_path]
        return router_main(router_argv)

    from ..utils.config import (
        BatchingConfig,
        CacheConfig,
        CascadeConfig,
        ElasticConfig,
        FleetConfig,
        IntegrityConfig,
        KernelsConfig,
        LifecycleConfig,
        MeshConfig,
        ObservabilityConfig,
        OverloadConfig,
        QualityConfig,
        RecoveryConfig,
        TransportConfig,
        UtilizationConfig,
    )

    cfgs = load_config(args.config) if args.config else {"server": ServerConfig()}
    cfg = cfgs["server"]
    batching_config = cfgs.get("batching") or BatchingConfig()
    if args.stream_chunk is not None:
        batching_config = dataclasses.replace(
            batching_config, stream_chunk_candidates=max(args.stream_chunk, 0)
        )
    transport_config = cfgs.get("transport") or TransportConfig()
    if args.uds_path:
        transport_config = dataclasses.replace(
            transport_config, uds_path=args.uds_path
        )
    obs = cfgs.get("observability") or ObservabilityConfig()
    if args.tracing:
        obs = dataclasses.replace(obs, tracing=True)
    cache_config = cfgs.get("cache") or CacheConfig()
    if args.cache:
        cache_config = dataclasses.replace(cache_config, enabled=True)
    overload_config = cfgs.get("overload") or OverloadConfig()
    if args.overload:
        overload_config = dataclasses.replace(overload_config, enabled=True)
    utilization_config = cfgs.get("utilization") or UtilizationConfig()
    if args.utilization:
        utilization_config = dataclasses.replace(
            utilization_config, enabled=True
        )
    quality_config = cfgs.get("quality") or QualityConfig()
    if args.quality:
        quality_config = dataclasses.replace(quality_config, enabled=True)
    lifecycle_config = cfgs.get("lifecycle") or LifecycleConfig()
    if args.lifecycle:
        lifecycle_config = dataclasses.replace(lifecycle_config, enabled=True)
    recovery_config = cfgs.get("recovery") or RecoveryConfig()
    if args.recovery:
        recovery_config = dataclasses.replace(recovery_config, enabled=True)
    kernels_config = cfgs.get("kernels") or KernelsConfig()
    if args.kernels:
        kernels_config = dataclasses.replace(kernels_config, enabled=True)
    fleet_config = cfgs.get("fleet") or FleetConfig()
    if args.fleet:
        fleet_config = dataclasses.replace(fleet_config, enabled=True)
    mesh_config = cfgs.get("mesh") or MeshConfig()
    if args.mesh:
        mesh_config = dataclasses.replace(mesh_config, enabled=True)
    elastic_config = cfgs.get("elastic") or ElasticConfig()
    if args.elastic:
        elastic_config = dataclasses.replace(elastic_config, enabled=True)
        if not mesh_config.enabled:
            # The --elastic FLAG implies the mesh mode it resizes (the
            # --lifecycle/--quality precedent: the flag user's intent is
            # unambiguous). A TOML-only [elastic] without [mesh] is NOT
            # auto-armed — a serving-topology change must never ride a
            # config omission; build_stack refuses it explicitly.
            mesh_config = dataclasses.replace(mesh_config, enabled=True)
    cascade_config = cfgs.get("cascade") or CascadeConfig()
    if args.cascade:
        cascade_config = dataclasses.replace(cascade_config, enabled=True)
    integrity_config = cfgs.get("integrity") or IntegrityConfig()
    if args.integrity:
        integrity_config = dataclasses.replace(integrity_config, enabled=True)
    if mesh_config.enabled:
        # With the mesh MODE armed, the CLI mesh-geometry flags configure
        # the [mesh] section (and are withheld from the legacy [server]
        # knobs below, which would otherwise trip the pick-one-surface
        # refusal in build_stack).
        mesh_overrides = {
            k: v for k, v in {
                "devices": args.mesh_devices,
                "model_parallel": args.model_parallel,
                "tensor_parallel": args.tensor_parallel,
            }.items() if v is not None
        }
        if mesh_overrides:
            mesh_config = dataclasses.replace(mesh_config, **mesh_overrides)
        args.mesh_devices = None
        args.model_parallel = None
        args.tensor_parallel = None
    if lifecycle_config.enabled and not quality_config.enabled:
        # --lifecycle implies the quality plane it reads: arming the
        # actuator without its signal would fail build_stack's check, and
        # the flag user's intent is unambiguous.
        quality_config = dataclasses.replace(quality_config, enabled=True)
    model_config = cfgs.get("model")
    if model_config is not None:
        # Explicit CLI architecture flags win over the TOML [model] section
        # (same precedence as the ServerConfig overrides below).
        arch_overrides = {
            k: v
            for k, v in {"num_fields": args.num_fields, "name": args.model_name}.items()
            if v is not None
        }
        if arch_overrides:
            model_config = dataclasses.replace(model_config, **arch_overrides)
    field_names = {f.name for f in dataclasses.fields(ServerConfig)}
    overrides = {
        k: v for k, v in vars(args).items() if v is not None and k in field_names
    }
    if args.no_warmup:
        overrides["warmup"] = False
    if args.version_label_args:
        pairs = []
        for raw in args.version_label_args:
            label, sep, version = raw.partition("=")
            try:
                pairs.append((label, int(version)))
            except ValueError:
                sep = ""
            if not sep or not label:
                raise SystemExit(
                    f"--version-label expects LABEL=VERSION, got {raw!r}"
                )
        # CLI labels replace the TOML map entirely (same precedence as the
        # scalar overrides above).
        overrides["version_labels"] = tuple(sorted(pairs))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    if args.batching_parameters_file:
        from ..utils.config import apply_batching_parameters

        cfg = apply_batching_parameters(cfg, args.batching_parameters_file)
    # Parse/validate BEFORE the (expensive) stack build: a typo'd PEM must
    # fail in milliseconds, not after checkpoint load + warmup compiles.
    credentials = (
        load_ssl_credentials(args.ssl_config_file)
        if args.ssl_config_file else None
    )

    logging.basicConfig(level=logging.INFO)
    registry, batcher, impl, servable, mesh, watcher = build_stack(
        cfg,
        checkpoint=args.checkpoint,
        savedmodel=args.savedmodel,
        model_config=model_config,
        model_base_path=args.model_base_path,
        cache_config=cache_config,
        overload_config=overload_config,
        utilization_config=utilization_config,
        quality_config=quality_config,
        lifecycle_config=lifecycle_config,
        batching_config=batching_config,
        transport_config=transport_config,
        recovery_config=recovery_config,
        kernels_config=kernels_config,
        mesh_config=mesh_config,
        elastic_config=elastic_config,
        cascade_config=cascade_config,
        integrity_config=integrity_config,
    )
    if impl.lifecycle is not None:
        # The CLI server drives the controller with its background thread
        # (ticks + the fine-tune publisher cadence); embedded callers and
        # tests drive tick() themselves.
        impl.lifecycle.start()
    if impl.recovery is not None:
        # Watchdog thread: escalates the batcher's wedge clock into a
        # quarantine decision on its poll cadence; failure-triggered
        # cycles wake it early.
        impl.recovery.start()
    # ONE teardown path for every exit: SIGTERM, REST-startup failure, and
    # normal termination all drain through this (admissions refused, queued
    # + in-flight work answered up to [overload] drain_grace_s, transport
    # stopped with the remaining grace).
    shutdown = GracefulShutdown(
        impl, batcher,
        grace_s=overload_config.drain_grace_s,
        watcher=watcher,
        lifecycle=impl.lifecycle,
        recovery=impl.recovery,
    )
    request_logger = None
    if cfg.request_log_file:
        from .request_log import RequestLogger

        request_logger = RequestLogger(
            cfg.request_log_file, sampling_rate=cfg.request_log_sampling
        )
        impl.request_logger = request_logger
        shutdown.request_logger = request_logger
        log.info("request logging to %s (sampling %.4f)",
                 cfg.request_log_file, cfg.request_log_sampling)
    if obs.apply() is not None:
        log.info(
            "per-request tracing on (buffer=%d sample_rate=%.3f slowest_n=%d)"
            " — GET /tracez on the REST surface",
            obs.trace_buffer, obs.trace_sample_rate, obs.trace_slowest_n,
        )
    metrics = ServerMetrics(window_s=obs.window_seconds)
    server, port = create_server(
        impl, f"{cfg.host}:{cfg.port}", cfg.max_workers, metrics,
        credentials=credentials,
        uds_path=transport_config.uds_path or None,
    )
    server.start()
    if transport_config.uds_path:
        log.info("gRPC also on unix:%s (co-located transport)",
                 transport_config.uds_path)
    shutdown.server = server
    # SIGTERM = drain: health NOT_SERVING, new admissions refused
    # UNAVAILABLE("draining"), accepted work answered up to the grace.
    shutdown.install_signal_handler()
    if fleet_config.enabled:
        from ..fleet import gossip as fleet_gossip
        from ..fleet.replica import ReplicaFleetPlane

        # The gossip id defaults to this replica's serving address — the
        # SAME string the router lists in its [client] hosts, so a gossip
        # record steers the router's scoreboard without any id mapping.
        fleet_self_id = fleet_config.self_id or f"{cfg.host}:{port}"

        def _fleet_record() -> dict:
            # Published every gossip interval: cheap reads only.
            if impl.draining:
                state = fleet_gossip.DRAINING
            elif impl.recovery is not None and impl.recovery.not_serving():
                state = fleet_gossip.QUARANTINED
            elif not (impl.warmup_complete and registry.models()):
                state = fleet_gossip.STARTING
            else:
                state = fleet_gossip.SERVING
            rec = {
                "state": state,
                "versions": tuple(registry.models().get(cfg.model_name, ())),
            }
            ov = impl.overload_stats()
            if ov:
                rec["pressure"] = str(ov.get("state") or "")
            if impl.integrity is not None:
                # Integrity verdict (ISSUE 20): suspect rides every
                # gossip record so routers steer around a replica whose
                # shadow verification caught its device miscomputing —
                # cleared (and re-gossiped False) after the configured
                # number of clean shadow passes.
                rec["suspect"] = bool(impl.integrity.suspect)
            if impl.lifecycle is not None:
                rec.update(impl.lifecycle.fleet_record())
            # Observability digest (ISSUE 18): qps/latency summary +
            # scrape address piggybacked on every gossip record, so the
            # router's fleet aggregate degrades to these numbers instead
            # of dropping this member when the /monitoring scrape fails.
            plane = impl.fleet
            rec["obs"] = {
                **metrics.fleet_summary(),
                "addr": plane.agent.listen_addr if plane is not None else "",
                "trace_export": bool(obs.tracing and obs.trace_export),
            }
            return rec

        def _trace_export_route(query: dict) -> dict:
            # GET /tracez/export?since=CURSOR on the gossip port: kept
            # span trees for the router's TraceCollector. Gated on the
            # [observability] trace_export knob (off by default).
            if not (obs.tracing and obs.trace_export) or not tracing.enabled():
                return {"enabled": False, "cursor": 0, "spans": []}
            try:
                since = int(query.get("since", 0) or 0)
            except (TypeError, ValueError):
                since = 0
            return tracing.recorder().export_since(since)

        fleet_plane = ReplicaFleetPlane(
            dataclasses.replace(fleet_config, self_id=fleet_self_id),
            record_fn=_fleet_record,
            lifecycle=impl.lifecycle,
            extra_routes={"/monitoring": metrics.fleet_wire},
            query_routes={"/tracez/export": _trace_export_route},
        )
        impl.fleet = fleet_plane
        shutdown.fleet = fleet_plane
        fleet_plane.start()
        log.info(
            "fleet plane up (id=%s gossip=%s peers=%d rollout_follow=%s)",
            fleet_self_id, fleet_plane.agent.listen_addr,
            len(fleet_config.peers), impl.lifecycle is not None,
        )
    if credentials is not None:
        log.info("gRPC port is TLS-secured (--ssl-config-file)")
    if args.rest_port:
        try:
            bound = start_rest_in_thread(impl, cfg.host, args.rest_port, metrics)
        except RuntimeError as exc:
            shutdown.shutdown()
            raise SystemExit(str(exc)) from exc
        log.info("REST gateway on %s:%d (/v1/models/...)", cfg.host, bound)
    log.info(
        "PredictionService on %s:%d (model=%s kind=%s mesh=%s devices=%s)",
        cfg.host, port, servable.name if servable else "<awaiting versions>",
        cfg.model_kind, dict(mesh.shape) if mesh else None, jax.devices(),
    )
    try:
        if args.metrics_every_s > 0:
            # grpc's wait_for_termination(timeout) returns True when the
            # timeout elapsed with the server still live, False once it
            # terminates — periodic logging AND termination detection in one
            # loop (verified against grpcio 1.76 behavior).
            while server.wait_for_termination(timeout=args.metrics_every_s):
                snap = metrics.snapshot(batcher.stats)
                snap["phases"] = request_trace.snapshot()
                log.info("metrics %s", json.dumps(snap))
        else:
            server.wait_for_termination()
    finally:
        log.info("shutting down")
        # Same drain path as SIGTERM (no-op if the signal already ran it:
        # shutdown() is idempotent and blocks until the first run finishes).
        shutdown.shutdown()


if __name__ == "__main__":
    serve()
