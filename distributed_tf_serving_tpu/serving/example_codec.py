"""tf.Example -> dense feature-batch decoding for the Classify/Regress/
MultiInference Input path (reference surface: input.proto:15-76 and
feature.proto:65-105 — Input carries an ExampleList or
ExampleListWithContext).

Convention: each Example carries an int64 "feat_ids" list and an optional
float "feat_wts" list, both of length num_fields (absent weights default to
1.0), mirroring the dense request contract (DCNClient.java:98-108). With
ExampleListWithContext, context features fill in whatever a per-candidate
Example omits — the two-tower pattern: user fields once in the context,
item fields per candidate.
"""

from __future__ import annotations

import numpy as np

from ..proto import serving_apis_pb2 as apis
from ..proto import tf_example_pb2 as ex


class ExampleDecodeError(ValueError):
    pass


def _merged_feature(example: ex.Example, context: ex.Example | None, key: str):
    if key in example.features.feature:
        return example.features.feature[key]
    if context is not None and key in context.features.feature:
        return context.features.feature[key]
    return None


def decode_input(
    inp: apis.Input, num_fields: int, arena=None
) -> dict[str, np.ndarray]:
    """Decode a serving Input into the dense feat_ids/feat_wts batch.

    `arena` (codec.EncodeArena) reuses the dense batch buffers across
    calls instead of allocating per request — safe because the batcher's
    prepare_inputs copies writable arrays before submit() returns, and
    arenas are held per thread."""
    kind = inp.WhichOneof("kind")
    if kind == "example_list":
        examples, context = list(inp.example_list.examples), None
    elif kind == "example_list_with_context":
        examples = list(inp.example_list_with_context.examples)
        context = inp.example_list_with_context.context
    else:
        raise ExampleDecodeError("Input has neither example_list nor example_list_with_context")
    if not examples:
        raise ExampleDecodeError("Input contains no examples")

    n = len(examples)
    if arena is not None:
        ids = arena.ndarray((n, num_fields), np.int64)
        ids[:] = 0
        wts = arena.ndarray((n, num_fields), np.float32)
        wts[:] = 1.0
    else:
        ids = np.zeros((n, num_fields), np.int64)
        wts = np.ones((n, num_fields), np.float32)
    for i, example in enumerate(examples):
        f_ids = _merged_feature(example, context, "feat_ids")
        if f_ids is None or f_ids.WhichOneof("kind") != "int64_list":
            raise ExampleDecodeError(f"example {i}: missing int64 feature 'feat_ids'")
        vals = f_ids.int64_list.value
        if len(vals) != num_fields:
            raise ExampleDecodeError(
                f"example {i}: feat_ids has {len(vals)} values, model expects {num_fields}"
            )
        ids[i] = vals

        f_wts = _merged_feature(example, context, "feat_wts")
        if f_wts is not None:
            if f_wts.WhichOneof("kind") != "float_list":
                raise ExampleDecodeError(f"example {i}: feat_wts must be a float_list")
            wvals = f_wts.float_list.value
            if len(wvals) != num_fields:
                raise ExampleDecodeError(
                    f"example {i}: feat_wts has {len(wvals)} values, model expects {num_fields}"
                )
            wts[i] = wvals
    return {"feat_ids": ids, "feat_wts": wts}


def make_example(ids, wts=None) -> ex.Example:
    """Build a feat_ids/feat_wts Example (client + test helper)."""
    example = ex.Example()
    example.features.feature["feat_ids"].int64_list.value.extend(int(i) for i in ids)
    if wts is not None:
        example.features.feature["feat_wts"].float_list.value.extend(float(w) for w in wts)
    return example
