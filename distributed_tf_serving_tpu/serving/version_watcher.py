"""Filesystem model-version lifecycle — TF-Serving's base-path convention.

The reference delegates model loading/versioning to tensorflow_model_server
(SURVEY.md §0 "implicit capabilities": model.proto:9-19 latest-version
semantics), whose operational contract is a *base path* containing numeric
version subdirectories: `<base>/1/`, `<base>/2/`, ... — the server loads the
newest, hot-swaps when a new version directory appears, and unloads retired
ones without dropping traffic. This module is that contract for the TPU
runtime:

- each version directory is either a native checkpoint
  (train/checkpoint.py layout: servable.json + params/) or a TF SavedModel
  export (saved_model.pb + variables/ — imported via interop/savedmodel.py);
- a poller thread diffs the directory against loaded versions, loads new
  ones (warming the batcher's bucket ladder BEFORE registering, so the
  version flip never serves a cold cache), and unloads versions that fell
  out of the retention window;
- `ServableRegistry.resolve`'s latest-version default makes the swap atomic
  from the client's view: requests pin a version or follow the newest.
"""

from __future__ import annotations

import dataclasses
import logging
import pathlib
import threading
from typing import Callable

from ..models.registry import (
    ModelNotFoundError,
    Servable,
    ServableRegistry,
    VersionNotFoundError,
)

log = logging.getLogger("dts_tpu.versions")


def scan_versions(base_path) -> dict[int, pathlib.Path]:
    """Numeric subdirectories of the base path (TF-Serving's convention;
    non-numeric entries are ignored, matching upstream behavior).

    Transient filesystem errors are SURVIVABLE by design: deploy tooling
    swaps version directories while this scan runs, so an ENOENT
    mid-listing or a stat race on a dir being replaced must degrade to
    "saw nothing (or less) this tick" and let the next poll retry — never
    propagate and kill the watcher thread (or the caller's startup scan)."""
    base = pathlib.Path(base_path)
    out: dict[int, pathlib.Path] = {}
    try:
        if not base.is_dir():
            return {}
        for child in base.iterdir():
            try:
                if child.is_dir() and child.name.isdigit():
                    out[int(child.name)] = child
            except OSError:
                continue  # entry vanished mid-scan: as if never listed
    except OSError as exc:
        log.warning(
            "transient filesystem error scanning %s (%s); retrying next tick",
            base_path, exc,
        )
    return out


def is_native_checkpoint(path: pathlib.Path) -> bool:
    return (path / "servable.json").exists()


def is_saved_model(path: pathlib.Path) -> bool:
    return (path / "saved_model.pb").exists()


def _version_ready(path: pathlib.Path) -> bool:
    """Only load fully-written versions. Native checkpoints commit by
    writing servable.json AFTER params/ (train/checkpoint.py write order),
    so manifest + params presence means complete. SavedModel exports are
    ready once variables/variables.index exists — TF writes the index after
    the data shards, so probing for the directory alone can fire while
    shards are still streaming in (ADVICE.md round 1)."""
    try:
        if is_native_checkpoint(path):
            return (path / "params").exists()
        if is_saved_model(path):
            # Strictly require the index: an empty variables/ dir is exactly
            # what a writer that has created the dir but not yet streamed the
            # shards looks like, so it must not probe ready.
            return (path / "variables" / "variables.index").exists()
    except OSError:
        # Version dir swapped out from under the probe: not ready this
        # tick; the next poll sees the final state.
        pass
    return False


def _version_mtime(path: pathlib.Path) -> int:
    """Newest mtime under the version dir (1 level deep) — cheap change
    signal used to un-blacklist a version once its writer finishes."""
    try:
        stamps = [path.stat().st_mtime_ns]
        for child in path.iterdir():
            stamps.append(child.stat().st_mtime_ns)
            if child.is_dir():
                stamps.extend(g.stat().st_mtime_ns for g in child.iterdir())
        return max(stamps)
    except OSError:
        return 0


@dataclasses.dataclass
class VersionWatcherConfig:
    poll_interval_s: float = 5.0
    keep_versions: int = 2  # retention window, newest-first
    model_name: str = "DCN"
    model_kind: str = "dcn_v2"  # for SavedModel version dirs
    # Transient failures (e.g. a slow writer racing the readiness probe)
    # get this many polls before the version is blacklisted for good.
    max_load_attempts: int = 3
    # The generic embed+MLP import fallback stays OFF on this path by
    # default: the watcher hot-swaps versions into live traffic with no
    # operator in the loop, and silently serving an export under a
    # DIFFERENT model family than configured is exactly the kind of
    # plausible-scores/wrong-math surprise an auto-rollout must not spring.
    # Explicit import_savedmodel calls (operator present) default it on.
    allow_generic_fallback: bool = False
    # Startup (label, version) assignments, applied ONCE each as their
    # version becomes loadable (retried while pending). Seed-once, not
    # continuous enforcement: after a label is assigned, runtime owners
    # (ModelService HandleReloadConfigRequest) may retarget or drop it and
    # the watcher must not fight them back every poll.
    desired_labels: tuple[tuple[str, int], ...] = ()


class VersionWatcher:
    """Poll a base path; keep the registry serving its newest versions.

    `loader(version, path) -> Servable` is injected so serving policy
    (mesh placement, import config, warmup) stays with the caller; the
    default loader handles both directory flavors.
    """

    def __init__(
        self,
        base_path,
        registry: ServableRegistry,
        config: VersionWatcherConfig | None = None,
        loader: Callable[[int, pathlib.Path], Servable] | None = None,
        warmup: Callable[[Servable], None] | None = None,
        # warmup_replay(servable, warmup_file) replays the version's own
        # assets.extra/tf_serving_warmup_requests records (serving/warmup
        # .py) after the synthetic bucket warmup, still BEFORE the registry
        # flip; a corrupt/failing file fails the load like upstream.
        warmup_replay: Callable[[Servable, pathlib.Path], int] | None = None,
        model_config=None,  # ModelConfig for SavedModel version dirs
        mesh=None,  # restore-time placement for native checkpoints
        tensor_parallel: bool = False,
        # on_servable_change(model_name) fires after every registry
        # mutation this watcher performs (version loaded or retired) —
        # the cache plane's generation-invalidation hook: a version swap
        # must drop the old generation's cached scores the moment the
        # registry flips, not at TTL expiry. Must not raise; exceptions
        # are logged and never fail the load/retire that triggered them.
        on_servable_change: Callable[[str], None] | None = None,
    ):
        self.base_path = pathlib.Path(base_path)
        self.registry = registry
        self.config = config or VersionWatcherConfig()
        self.loader = loader or self._default_loader
        self.warmup = warmup
        self.warmup_replay = warmup_replay
        self.model_config = model_config
        self.mesh = mesh
        self.tensor_parallel = tensor_parallel
        self.on_servable_change = on_servable_change
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="version-watcher", daemon=True
        )
        self._attempts: dict[int, int] = {}  # version -> failed load count
        self._attempt_mtime: dict[int, int] = {}  # version -> mtime at last failure
        self._label_warned: set[str] = set()  # once-per-label pending warning
        self._labels_applied: set[str] = set()  # seed-once bookkeeping
        # Programmatic lifecycle control (serving/lifecycle.py rollback):
        # blacklisted versions are EXCLUDED from the reconcile candidate
        # set — unlike the mtime-keyed load-failure backoff above, an
        # explicit blacklist never self-clears when the directory changes
        # (a rolled-back version must not reload because a writer touched
        # it); pinned versions are exempt from retention (a live canary's
        # rollback target must outlive newer rollouts). Mutations REBIND
        # a fresh frozenset (never mutate in place): the controller
        # thread writes while the poll thread and snapshot() iterate, and
        # an in-place set.add during iteration raises "changed size
        # during iteration" — atomic rebinds make every reader see a
        # consistent immutable view, no lock needed.
        self._blacklisted: frozenset[int] = frozenset()
        self._pinned: frozenset[int] = frozenset()
        # Last reconcile pass's on-disk-ready versions: snapshot()
        # reports this CACHED view instead of re-scanning the base path —
        # a monitoring scrape must never pay (or hang on) filesystem I/O.
        self._last_ready: tuple[int, ...] = ()

    # ----------------------------------------------------------------- API

    def start(self) -> "VersionWatcher":
        self.poll_once()  # synchronous first scan: serve something at start
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Signal without joining (multi-watcher shutdown signals ALL
        first so total drain time is the max, not the sum)."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    # ----------------------------------------------- lifecycle control API

    def blacklist(self, version: int) -> None:
        """Exclude `version` from the reconcile candidate set until
        unblacklisted — the rollback half-fix for the standing hazard
        where a retired bad version is simply reloaded on the next scan
        (its directory is still on disk and still probes ready)."""
        self._blacklisted = self._blacklisted | {int(version)}
        log.info("blacklisted %s v%d (excluded from reconcile)",
                 self.config.model_name, int(version))

    def unblacklist(self, version: int) -> None:
        self._blacklisted = self._blacklisted - {int(version)}

    def is_blacklisted(self, version: int) -> bool:
        return int(version) in self._blacklisted

    def pin(self, version: int) -> None:
        """Exempt `version` from retention (like a label pin, without a
        label): a canary's rollback target must not be retired out from
        under it by newer rollouts."""
        self._pinned = self._pinned | {int(version)}

    def unpin(self, version: int) -> None:
        self._pinned = self._pinned - {int(version)}

    def retire(self, version: int, blacklist: bool = True) -> bool:
        """Unload `version` from the registry NOW (traffic snaps to the
        remaining latest via resolve's default) and, by default,
        blacklist it so the next reconcile pass cannot hot-load it back
        from disk. True = a loaded version was actually unloaded."""
        v = int(version)
        if blacklist:
            self.blacklist(v)
        self.unpin(v)
        name = self.config.model_name
        try:
            self.registry.unload(name, v)
        except KeyError:  # Model/VersionNotFoundError: never loaded
            return False
        log.info("retired %s v%d (lifecycle)", name, v)
        self._notify_change(name)
        return True

    def snapshot(self) -> dict:
        """Watcher state for /monitoring and the lifecycle block: what is
        loaded, what the LAST reconcile pass saw ready on disk (cached —
        a monitoring scrape must not pay, or hang on, filesystem I/O),
        and the blacklist/pin sets."""
        name = self.config.model_name
        # _attempts is mutated in place by the poll thread; copying a
        # resizing dict can raise "changed size during iteration" on
        # this (scrape) thread. Bounded retries; an empty fallback beats
        # failing the surface at exactly the failing-load moment an
        # operator is looking for.
        attempts: dict[int, int] = {}
        for _ in range(3):
            try:
                attempts = dict(self._attempts)
                break
            except RuntimeError:
                continue
        return {
            "base_path": str(self.base_path),
            "model": name,
            "loaded": sorted(self.registry.models().get(name, ())),
            "on_disk_ready": list(self._last_ready),
            "blacklisted": sorted(self._blacklisted),
            "pinned": sorted(self._pinned),
            "failed_attempts": dict(sorted(attempts.items())),
        }

    def poll_once(self) -> None:
        """One reconcile pass: load new ready versions, retire old ones.

        Load candidates are the newest `keep_versions` READY versions on
        disk (TF-Serving's latest-N version policy). Considering every
        unloaded on-disk version would re-load each retired one on every
        poll — a continuous load/compile/unload storm competing with live
        traffic once history outgrows the retention window (the round-1
        advisor's high-severity finding)."""
        name = self.config.model_name
        on_disk = scan_versions(self.base_path)
        loaded = set(self.registry.models().get(name, ()))

        ready_on_disk = {v: p for v, p in on_disk.items() if _version_ready(p)}
        # Cached for snapshot(): the monitoring surfaces report what THIS
        # pass saw instead of re-scanning the base path per scrape. The
        # cache deliberately includes blacklisted versions — "the bad dir
        # still sits ready on disk" is exactly the state worth seeing.
        self._last_ready = tuple(sorted(ready_on_disk))
        # Blacklisted versions (lifecycle rollback) never re-enter the
        # candidate set, however ready their directories look — without
        # this, a rolled-back version would be hot-loaded straight back
        # on the next scan.
        ready = {
            v: p for v, p in ready_on_disk.items()
            if v not in self._blacklisted
        }
        candidates = sorted(ready, reverse=True)[: self.config.keep_versions]
        for version in sorted(v for v in candidates if v not in loaded):
            if self._stop.is_set():
                # A mid-load stop (runtime model removal) must not let this
                # thread register versions AFTER the caller unloads the
                # model — a timed-out join would otherwise race a zombie
                # load back into the registry.
                return
            path = ready[version]
            if self._attempts.get(version, 0) >= self.config.max_load_attempts:
                # Blacklisted — but a writer that finished late changes the
                # directory; give the version a fresh set of attempts then,
                # so recovery never requires a server restart.
                mtime = _version_mtime(path)
                if mtime == self._attempt_mtime.get(version):
                    continue
                self._attempts.pop(version, None)
                self._attempt_mtime.pop(version, None)
            # Snapshot BEFORE loading: a writer finishing mid-attempt would
            # otherwise be recorded at its final mtime, making the blacklist
            # look current forever (no restart-free recovery).
            pre_mtime = _version_mtime(path)
            try:
                servable = self.loader(version, path)
                if self.warmup is not None:
                    self.warmup(servable)  # cold-cache work BEFORE the flip
                if self.warmup_replay is not None:
                    from .warmup import warmup_file_for

                    wf = warmup_file_for(path)
                    if wf is not None:
                        n = self.warmup_replay(servable, wf)
                        log.info(
                            "replayed %d warmup records for %s v%d", n, name, version
                        )
                if self._stop.is_set():
                    return  # stopped while loading: never register (above)
                self.registry.load(servable)
                self._attempts.pop(version, None)
                self._attempt_mtime.pop(version, None)
                log.info("loaded %s v%d from %s", name, version, path)
                self._notify_change(name)
            except Exception:
                self._attempts[version] = self._attempts.get(version, 0) + 1
                self._attempt_mtime[version] = pre_mtime
                log.exception(
                    "failed to load %s v%d from %s (attempt %d/%d)",
                    name, version, path,
                    self._attempts[version], self.config.max_load_attempts,
                )

        # Retention: keep the newest K of the union PLUS any labeled
        # version — a pinned "stable" must not be retired out from under
        # its label by newer rollouts (blue-green would silently break).
        # Pins follow the registry's LIVE label state (runtime retargets
        # release old pins) plus not-yet-seeded startup labels.
        loaded = set(self.registry.models().get(name, ()))
        # Defensive sweep: a blacklisted version that is somehow still
        # loaded (blacklisted externally, or loaded by another control
        # path) is retired now — the blacklist means "do not serve".
        for version in sorted(loaded & self._blacklisted):
            try:
                self.registry.unload(name, version)
            except KeyError:
                # The lifecycle thread's retire() unloaded it between
                # this pass's registry read and now — already gone is
                # the goal state, not a failed pass.
                pass
            else:
                log.info("retired %s v%d (blacklisted)", name, version)
                self._notify_change(name)
            loaded.discard(version)
        pinned = set(self.registry.labels(name).values()) | set(
            self._pinned
        ) | {
            v for l, v in self.config.desired_labels
            if l not in self._labels_applied
        }
        keep = set(sorted(loaded, reverse=True)[: self.config.keep_versions])
        keep |= pinned & loaded
        for version in sorted(loaded - keep):
            self.registry.unload(name, version)
            log.info("retired %s v%d (retention window %d)",
                     name, version, self.config.keep_versions)
            self._notify_change(name)

        # Startup-label seeding: assign each desired label the moment its
        # version is loaded, ONCE (retrying only while pending) — from then
        # on the label belongs to runtime control (reload-config RPC).
        for label, version in self.config.desired_labels:
            if label in self._labels_applied:
                continue
            try:
                self.registry.set_label(name, label, version)
                self._labels_applied.add(label)
                log.info("label %r -> %s v%d", label, name, version)
            except (ModelNotFoundError, VersionNotFoundError):
                if label not in self._label_warned:
                    self._label_warned.add(label)
                    log.warning(
                        "label %r wants %s v%d, which is not loaded yet; "
                        "will keep trying each poll", label, name, version,
                    )

    # ------------------------------------------------------------ internals

    def _notify_change(self, name: str) -> None:
        """Fire the servable-change hook; a hook failure must never fail
        the load/retire that triggered it."""
        if self.on_servable_change is None:
            return
        try:
            self.on_servable_change(name)
        except Exception:  # noqa: BLE001 — hook bugs stay out of the lifecycle
            log.exception("on_servable_change hook failed for %s", name)

    def _default_loader(self, version: int, path: pathlib.Path) -> Servable:
        import dataclasses as dc

        if is_native_checkpoint(path):
            from ..train.checkpoint import load_servable

            servable = load_servable(
                path, mesh=self.mesh, tensor_parallel=self.tensor_parallel
            )
        else:
            from ..interop import import_savedmodel
            from ..interop.savedmodel import SavedModelImportError
            from ..models.base import ModelConfig

            try:
                servable = import_savedmodel(
                    path,
                    self.config.model_kind,
                    self.model_config or ModelConfig(name=self.config.model_name),
                    name=self.config.model_name,
                    version=version,
                    fallback=self.config.allow_generic_fallback,
                )
            except SavedModelImportError as exc:
                if self.model_config is None:
                    # The likeliest cause of a binding failure here is an
                    # architecture that differs from the DEFAULT ModelConfig
                    # this watcher fell back to — say so, instead of letting
                    # a bare shape-mismatch blame the export (VERDICT r2
                    # weak #7).
                    raise SavedModelImportError(
                        f"{exc}\n(this VersionWatcher was constructed without "
                        "a model_config, so the import assumed the default "
                        f"{self.config.model_kind!r} architecture "
                        f"{ModelConfig(name=self.config.model_name)!r}; if "
                        "the export's num_fields/vocab_size/embed_dim/"
                        "mlp_dims differ, pass model_config / the TOML "
                        "[model] section)"
                    ) from exc
                raise
        # The directory number is authoritative (TF-Serving semantics),
        # whatever version the artifact itself recorded.
        if servable.version != version or servable.name != self.config.model_name:
            servable = dc.replace(
                servable, version=version, name=self.config.model_name
            )
        return servable

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_interval_s):
            try:
                self.poll_once()
            except Exception:
                log.exception("version poll failed; retrying next interval")
