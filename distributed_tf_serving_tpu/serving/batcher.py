"""Dynamic batching engine — the in-tree replacement for TF-Serving's
server-side batching (the reference claims it as a core capability,
README.md:5,9, but delegates it to the external tensorflow_model_server).

TPU-first design:

- **Padded candidate buckets.** XLA compiles one executable per input shape,
  so arbitrary candidate counts would cause a compile storm. Incoming work is
  padded up to a fixed bucket ladder (powers of two by default); jax.jit's
  own trace cache then keys on the bucket shape, giving exactly one compiled
  executable per (servable, bucket).
- **Request coalescing.** Concurrent small requests targeting the same
  (servable, signature) are concatenated along the candidate axis into one
  device call, then split back — amortizing dispatch overhead exactly like
  TF-Serving's BatchingSession. At low load a request waits at most
  `max_wait_us` before dispatch; under sustained load the window is
  *pipeline-aware*: while >= `pipeline_depth` batches are already in
  flight, dispatching another partial batch would only queue behind device
  work, so the batcher keeps filling past the deadline for free — latency
  is unchanged (the dispatch would have waited anyway) and occupancy rises
  toward full buckets.
- **Host-side id folding.** Wire ids are int64 (DCNClient.java:98-102) but
  jax runs x64-disabled; ids are folded into the vocab with int64 numpy on
  the host (exact `mod`, not truncation) before device transfer, which also
  shrinks the transfer 2x.

- **Transfer-optimized output path.** The jitted entry returns only the
  requested output tensors, downcast on-device to a configurable wire dtype
  (bf16/f16; float32 = the exact fallback) — and, for retrieval-style
  single-request batches, only the top-k (score, index) pairs — so the D2H
  link never carries full fp32 output tensors. The D2H copy is *issued* at
  dispatch time (`readback.issue`) and only *awaited* on a completer thread
  (`readback.wait`), so the transfer overlaps host work instead of
  serializing behind it.

The core is a dedicated batching thread with a thread-safe queue, so it
serves both the sync grpc server (handler threads block on a Future) and the
asyncio server (await wrap_future). Device work is serialized: in pipelined
mode (default) the batching thread collects+pads while ONE dispatch thread
runs the device stage (cache/pack/upload/jit-call) — batch k+1's H2D upload
starts while batch k executes — and with pipelining off both stages share
the batching thread exactly as before.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading
import time
import weakref
from collections.abc import Callable
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError, ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults
from . import overload as overload_mod
from ..cache import CoalescedLeaderCancelled, collapse_rows
from ..cache.digest import canonical_rows
from ..models.base import Model
from ..models.registry import Servable
from ..ops.transfer import (
    cascade_prune_device,
    combined_layout,
    combined_supported,
    compact_outputs_device,
    is_wire_sidecar,
    output_wire_dtype as _wire_dtype_of,
    pack_host,
    pack_host_combined,
    restore_outputs_host,
    topk_compact_device,
    topk_restore_host,
    transfer_spec,
    unpack_device,
    unpack_device_combined,
)
from ..utils.compat import enable_x64
from ..utils import tracing
from ..utils.tracing import request_trace
from .integrity import IntegrityScreenError

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096)

# Reusable (stateless) no-op context for the non-x64 hot path.
_NULL_CTX = contextlib.nullcontext()


class BatchTooLargeError(ValueError):
    pass


class QueueOverloadError(RuntimeError):
    """Queue admission refused: accepting more work would only build a
    backlog no deadline survives. Maps to RESOURCE_EXHAUSTED at the RPC
    layer — shedding beats queueing past the client's deadline."""


class AdmissionRefusedError(QueueOverloadError):
    """The adaptive overload plane (serving/overload.py) refused this
    request: capacity/lane shedding (`reason` "shed") or doomed-work
    refusal ("doomed" — the backlog's estimated wait already exceeds the
    request's remaining deadline budget). Carries the retry-after-ms
    pushback hint the RPC layer forwards in trailing metadata. Subclasses
    QueueOverloadError so the status mapping (RESOURCE_EXHAUSTED) and
    every existing handler stay correct."""

    def __init__(self, message: str, reason: str = "shed",
                 retry_after_ms: int | None = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_ms = retry_after_ms


class DeviceWedgedError(RuntimeError):
    """Circuit breaker open: a dispatched batch has been stuck past the
    wedge threshold, so the device (or its compile path) is presumed hung.
    New work fails fast (UNAVAILABLE) instead of burning a handler thread
    per request for the full RPC deadline; the breaker closes by itself the
    moment the stuck batch completes."""


class DeviceQuarantinedError(DeviceWedgedError):
    """The recovery plane (serving/recovery.py) has quarantined this
    replica: the device executor is being torn down and rebuilt, so new
    work fails fast (UNAVAILABLE — fan-out clients reroute via the
    scoreboard) while the in-flight/queued work the replica already
    accepted rides the replay path instead of dying. Subclasses
    DeviceWedgedError so every existing status mapping and handler stays
    correct."""


class PoisonedInputError(ValueError):
    """This request's input deterministically kills the device executor:
    the recovery plane's bisection replayed progressively smaller
    sub-batches after repeated executor deaths and isolated THIS request
    as the culprit. A ValueError (-> INVALID_ARGUMENT at the RPC layer,
    the DISTINCT status the recovery contract promises): retrying the
    same bytes anywhere would kill another executor, so the client must
    not fail over with it — while the batchmates it took down are
    re-dispatched and succeed."""


class BatcherThreadDead(RuntimeError):
    """The batching loop, the pipelined dispatch stage, or a completer
    worker died from an unhandled exception. Every queued waiter is
    failed with this immediately and new submits raise it up front —
    submitters must never hang on the condition variable waiting for a
    thread that no longer exists. Maps to UNAVAILABLE (RuntimeError
    catch-all); the recovery plane, when armed, revives the thread and
    replays the shed work instead."""


def poison_fault_key(arrays: dict) -> str:
    """Content digest of one request's PREPARED input arrays (the bytes
    _WorkItem.arrays holds — post prepare_inputs, pre fold) — the `key`
    the device_lost fault site fires with once per batch member, so a
    keyed rule deterministically kills exactly the batches containing one
    specific request's content. Tests/soaks compute the same digest over
    the payload they submit to address their poison rule."""
    h = hashlib.blake2b(digest_size=8)
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        # uint8 view: ml_dtypes arrays refuse the buffer protocol
        # directly (the DeviceInputCache._key precedent).
        h.update(arr.view(np.uint8).data)
    return h.hexdigest()


def _inject_readback_corruption(host: dict, group: list) -> dict:
    """Named fault sites (faults.py): readback_bitflip / score_nan — the
    silent-corruption chaos the integrity plane (ISSUE 20) exists to
    catch. Fired once per member request with the same content digest
    device_lost uses, AFTER the D2H asarray so the corrupted bytes are
    exactly what readback handed the completer: a keyed rule
    deterministically flips one payload bit (shadow compare's prey) or
    NaN-poisons the member's score rows (the screen's prey). The error
    kinds are markers — the raise is caught HERE and applied as the
    corruption, never surfaced. Returns `host` with the score array
    replaced by a corrupted writable copy (np.asarray views of device
    buffers are read-only)."""
    fi = faults.get()
    score_key = group[0].servable.model.score_output
    scores = host.get(score_key)
    if scores is None:
        return host
    corrupted = None
    off = 0
    for it in group:
        n = it.n
        sl = slice(off, off + n)
        off += n
        key = poison_fault_key(it.arrays)
        for site in ("readback_bitflip", "score_nan"):
            if not fi.has_site(site):
                continue
            try:
                fi.fire(site, key=key)
            except faults.InjectedFaultError:
                if corrupted is None:
                    corrupted = np.ascontiguousarray(scores).copy()
                if site == "score_nan" and corrupted.dtype.kind == "f":
                    corrupted[sl] = np.nan
                else:
                    # One bit, lowest-order, first element of the row
                    # range — below any plausible-range screen's radar,
                    # exactly the divergence only a bit-identity compare
                    # detects.
                    flat = corrupted.reshape(-1).view(
                        np.dtype(f"u{corrupted.dtype.itemsize}")
                    )
                    stride = max(corrupted.size // max(len(scores), 1), 1)
                    flat[sl.start * stride] ^= 1
    if corrupted is not None:
        host = dict(host)
        host[score_key] = corrupted
    return host


class RequestDeadlineError(TimeoutError):
    """Queued work whose CLIENT deadline expired before a dispatch slot
    opened: shed instead of executed — the caller stopped listening, so the
    device time would buy nothing and delay everyone behind it. A
    TimeoutError so the service's translator maps it to DEADLINE_EXCEEDED."""


def bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise BatchTooLargeError(f"candidate count {n} exceeds largest bucket {buckets[-1]}")


def fold_ids_host(ids: np.ndarray, vocab_size: int) -> np.ndarray:
    """Exact int64 modulo fold on the host; models re-fold idempotently.
    Delegates to the one canonical fold (native.fold_ids) shared with the
    client's compact_payload."""
    from .. import native

    return native.fold_ids(ids, vocab_size)


def _immutably_backed(arr: np.ndarray) -> bool:
    """True only when the array's ULTIMATE buffer is a `bytes` object —
    the one backing genuinely immutable to every party (the serving path's
    np.frombuffer(proto.tensor_content) views). writeable=False alone is
    NOT enough: a frozen view over a writable base (broadcast_to,
    setflags(write=False)) can still see its bytes change under it, and
    even a read-only memoryview does not freeze its underlying bytearray/
    mmap — its owner can keep writing through the original object."""
    a = arr
    while isinstance(a.base, np.ndarray):
        a = a.base
    b = a.base
    if isinstance(b, memoryview):
        b = b.obj
    return isinstance(b, bytes)


def prepare_inputs(
    model: Model, arrays: dict[str, np.ndarray], fold_ids: bool = True
) -> dict[str, np.ndarray]:
    """Host-side normalization before padding/transfer.

    Every output array is OWNED or IMMUTABLE (never writable-aliased to the
    caller): submit() returns before the batch is padded/uploaded, so a
    caller mutating its array after submit() would race the async device
    transfer — and poison the content-addressed DeviceInputCache digest
    (round-1 advisor finding). fold/astype copy as a side effect; the
    passthrough branch skips the copy only for arrays whose backing buffer
    is itself immutable — the serving hot path's arrays are np.frombuffer
    views over protobuf bytes, which NOBODY can mutate (~50 us per 1k x 43
    request back on the 1-core host); anything else is copied.

    fold_ids=False defers the vocab fold to batch time (_execute folds the
    whole padded batch in ONE native call): per-request folding charged
    ~130 us of ctypes+alloc overhead per 1k-candidate request to the RPC
    thread/event loop — at 500 QPS that is ~7% of the single-core budget —
    while the batched fold costs the batcher thread ~150 us per 8k batch,
    GIL released. Callers that apply the model directly on the returned
    arrays (tests, measurement harnesses) keep the folding default: unfolded
    int64 would be silently int32-cast by device_put under x64-disabled
    JAX and re-fold into garbage for ids past 2^31."""
    out = {}
    for key, arr in arrays.items():
        if key == "feat_ids" and fold_ids and model.folds_ids_on_host:
            out[key] = fold_ids_host(arr, model.config.vocab_size)
        elif arr.dtype == np.float64 and not model.needs_x64:
            # Convenience downcast for the 32-bit zoo path only: an x64
            # model (graph executor with DT_DOUBLE inputs) must see the
            # doubles it was exported with.
            out[key] = arr.astype(np.float32)
        elif _immutably_backed(arr):
            out[key] = arr
        else:
            out[key] = arr.copy()
    return out


class DeviceInputCache:
    """Content-addressed LRU of device-resident input arrays.

    The serving hot path is host->device upload bound: a padded batch is
    ~0.2 KB/candidate and the link (PCIe, or this rig's relay tunnel) is the
    slowest hop in the stack. CTR traffic re-scores the same hot candidate
    sets continuously (the reference's own benchmark re-sends one payload for
    all 6,000 requests, DCNClient.java:208-210), so identical batch bytes
    recur. Keying the *device* array by a content digest of the packed host
    bytes lets a repeat batch skip the upload entirely — the jitted call gets
    an argument that is already resident in HBM.

    Misses cost one content digest (~0.1 ms/MB native, ~1.5 ms/MB blake2b
    fallback) plus the device_put the dispatch needed anyway; hits cost only
    the digest. Capacity is bounded by entry count (batches are ~1 MB;
    default 64 entries ~ 64 MB of a v5e's 16 GB HBM) with least-recently-used
    eviction.

    Traffic that never repeats would pay the digest for nothing, so the
    cache self-disables — and re-probes: the hit rate is tracked over a
    SLIDING window of `probe_window` lookups (not the process lifetime —
    a unique-traffic phase after a long repeated phase must still flip to
    pass-through, round-3 weak #3: the one-shot probe never fired because
    global hit rate stayed high). When a window's rate is below
    `min_hit_rate`, hashing stops; after `reprobe_every` bypassed lookups
    the cache re-enters probing so a traffic regime that turns repetitive
    again re-engages it (probing costs one window of digests per
    `reprobe_every` lookups, ~12% of digest cost while traffic stays
    unique).
    """

    def __init__(
        self,
        max_entries: int = 64,
        # 64-lookup windows: repeated traffic hits ~100% so false bypass
        # needs a 63/64-miss window (won't happen), while a unique phase
        # is detected within ~64 batches; reprobe_every=512 caps probing
        # overhead at ~11% of digest cost during sustained-unique traffic
        # and bounds regime-flip recovery to ~576 batches (~15 s at the
        # rig's batch cadence).
        probe_window: int = 64,
        min_hit_rate: float = 0.02,
        reprobe_every: int = 512,
    ):
        self.max_entries = max_entries
        self.probe_window = probe_window
        self.min_hit_rate = min_hit_rate
        self.reprobe_every = reprobe_every
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.bytes_skipped = 0
        self.bypassed = False
        self.bypass_cycles = 0
        self._win_hits = 0
        self._win_lookups = 0
        self._bypassed_lookups = 0

    def rearm(self) -> None:
        """Exit bypass immediately and restart the probe cycle — for
        callers that KNOW a traffic-regime boundary just happened (a bench
        phase change, a deployment cutover) and should not wait out the
        automatic re-probe cadence. One locked reset of the full counter
        set so external callers cannot drift from _note_bypassed's own
        re-arm sequence."""
        with self._lock:
            self.bypassed = False
            self._bypassed_lookups = 0
            self._win_hits = 0
            self._win_lookups = 0

    def _note_bypassed(self) -> None:
        """Count a pass-through lookup; periodically re-enter probing."""
        with self._lock:
            self._bypassed_lookups += 1
            if self._bypassed_lookups >= self.reprobe_every:
                self._bypassed_lookups = 0
                self._win_hits = 0
                self._win_lookups = 0
                self.bypassed = False

    @staticmethod
    def _key(name: str, arr: np.ndarray) -> tuple:
        from .. import native

        if native.available():
            digest = native.hash128(arr)  # ~5x blake2b, GIL released
        else:
            # uint8 view: ml_dtypes (bf16) arrays refuse the buffer
            # protocol directly ("cannot include dtype 'E'"), and the
            # digest is over raw bytes anyway.
            digest = hashlib.blake2b(
                np.ascontiguousarray(arr).view(np.uint8).data, digest_size=16
            ).digest()
        return (name, arr.shape, arr.dtype.str, digest)

    def get_or_put(
        self,
        name: str,
        arr: np.ndarray,
        pack: Callable[[np.ndarray], np.ndarray] | None = None,
        pack_tag: str = "",
    ) -> jax.Array | np.ndarray:
        """Device array for `arr`'s content, uploading (after `pack`, when
        given) only on miss. The digest keys on the PRE-pack bytes so a hit
        skips the transfer-compression work too. `pack` must be pure and
        `pack_tag` must identify the transform: the stored value is
        POST-pack, so the same raw bytes packed differently must occupy
        distinct entries."""
        if self.bypassed:
            self._note_bypassed()
            return pack(arr) if pack is not None else arr  # plain jit path
        key = (pack_tag, *self._key(name, arr))
        return self._lookup(key, lambda: pack(arr) if pack is not None else arr)

    def get_or_put_group(
        self,
        arrays: dict[str, np.ndarray],
        build: Callable[[], np.ndarray],
        tag: str,
    ) -> jax.Array | np.ndarray:
        """Device buffer for a GROUP of arrays (the combined-transfer path):
        keyed on every member's content digest plus `tag` (the layout), so a
        hit skips pack+concat+upload in one lookup. `build()` produces the
        combined host buffer only on miss."""
        if self.bypassed:
            self._note_bypassed()
            return build()
        key = (tag,) + tuple(self._key(k, arrays[k]) for k in sorted(arrays))
        return self._lookup(key, build)

    def _lookup(self, key: tuple, build_host: Callable[[], np.ndarray]):
        """Shared LRU hit/miss core: one implementation of the accounting,
        eviction, and the adaptive-bypass probe."""
        with self._lock:
            cached = self._lru.get(key)
            if cached is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                self._win_hits += 1
                self._close_window_locked()
                # The avoided upload is the stored (post-pack) size.
                self.bytes_skipped += cached.nbytes
                return cached
        device_arr = jax.device_put(build_host())  # async; the executable waits, not us
        with self._lock:
            self._lru[key] = device_arr
            self.misses += 1
            self._close_window_locked()
            while len(self._lru) > self.max_entries:
                self._lru.popitem(last=False)
        return device_arr

    def _close_window_locked(self) -> None:
        """Advance the sliding probe window; flip to bypass on a cold one.
        Caller holds _lock."""
        self._win_lookups += 1
        if self._win_lookups < self.probe_window:
            return
        if self._win_hits < self._win_lookups * self.min_hit_rate:
            self.bypassed = True
            self.bypass_cycles += 1
            self._bypassed_lookups = 0
            self._lru.clear()
        self._win_hits = 0
        self._win_lookups = 0

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()


class _HostBufferRing:
    """Reusable padded-batch host buffers (continuous-batching satellite).

    Every dispatched batch allocates one `np.empty((bucket,) + row_shape)`
    per input; at depth-k pipelining that is k live multi-MB allocations
    per model churning through the allocator while the device works. The
    ring hands back the SAME buffers once their batch fully completes —
    donation-safe by construction: a buffer is released only from the
    completer's finally (the batch's readback finished, so the H2D upload
    that read it is long done) or from a pre-device failure path, never
    while a transfer could still be reading it. The padding loops fully
    overwrite every acquired buffer (rows + zero tail), so stale content
    can never leak between batches.

    Bounded: at most `per_key` free buffers are retained per (shape,
    dtype) — an acquire beyond the ring is a plain allocation and its
    release is dropped on the floor (GC'd), so a bucket-ladder sweep
    cannot pin unbounded memory. Off by default (buffer_ring=False keeps
    the historical allocate-per-batch behavior)."""

    def __init__(self, per_key: int = 8):
        self.per_key = per_key
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self.reuses = 0
        self.allocs = 0

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        key = (shape, np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                self.reuses += 1
                return free.pop()
            self.allocs += 1
        return np.empty(shape, dtype)

    def release(self, arrs) -> None:
        with self._lock:
            for a in arrs:
                key = (a.shape, a.dtype.str)
                free = self._free.setdefault(key, [])
                if len(free) < self.per_key:
                    free.append(a)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "reuses": self.reuses,
                "allocs": self.allocs,
                "free_buffers": sum(len(v) for v in self._free.values()),
            }


class _RowContext:
    """One batch's row-granular cache consultation (ISSUE 14): the
    RowBatchPlan plus the index machinery that turns (cold device rows +
    cached hot rows + foreign in-flight fills) back into every request's
    original row order.

    - `inverse` maps each ORIGINAL row onto its execution-planning slot
      (identity when dedup found no duplicates); `lead_slots` are the
      slots this batch executes, in execution order, so cold row j of the
      device output is slot `lead_slots[j]`.
    - `passthrough` marks the degenerate plan — every row cold, no
      duplicates, no foreign flights to join — where execution covers the
      original batch in original order: the normal pad/fused/delivery
      paths serve it unchanged and only the cache fill rides along.
    """

    __slots__ = ("cache", "plan", "overload", "n_slots", "inverse",
                 "lead_slots", "n_cold", "exec_arrays", "passthrough",
                 "all_fresh")

    def fill_from_host(self, host: dict) -> None:
        """Close the plan's lead flights from the executed rows: fill the
        cache (same-generation only) and resolve every foreign waiter
        riding them. host arrays are post-readback, post-widen,
        post-sidecar-consume — exactly what delivery slices, so a later
        cache assembly is bit-identical to this execution."""
        values = {}
        for j, slot in enumerate(self.plan.lead):
            values[slot] = {
                k: np.array(v[j], copy=True) for k, v in host.items()
            }
        self.cache.complete_rows(self.plan, values)

    def abort(self, exc: BaseException) -> None:
        self.cache.abort_rows(self.plan, exc)

    def assemble(self, host: dict | None):
        """Full-batch outputs in ORIGINAL row order from the three row
        sources (executed / cached hit / foreign fill). Returns (full,
        failed_rows, row_errors): failed_rows is a bool mask over
        original rows whose foreign fill failed (their requests get the
        error, never a garbage score), row_errors maps failed slots to
        their exceptions. host None = the zero-cold batch."""
        plan = self.plan
        failed: dict[int, BaseException] = {}
        wvals: dict[int, dict] = {}
        for slot, fut in plan.waiters.items():
            if fut.cancelled():
                failed[slot] = CoalescedLeaderCancelled(
                    "row fill leader was cancelled before completing"
                )
                continue
            exc = fut.exception()
            if exc is not None:
                failed[slot] = exc
            else:
                wvals[slot] = fut.result()
        if host is not None:
            sample = {k: v[0] for k, v in host.items()}
        elif plan.hits:
            sample = next(iter(plan.hits.values()))
        elif wvals:
            sample = next(iter(wvals.values()))
        else:
            # Every slot rode a foreign flight and every one failed.
            raise next(iter(failed.values()))
        full = {}
        for k, v in sample.items():
            arr = np.asarray(v)
            # zeros, not empty: a failed slot's rows are never delivered,
            # but uninitialized memory must not be reachable even by bug.
            vals = np.zeros((self.n_slots,) + arr.shape, arr.dtype)
            if host is not None and self.n_cold:
                vals[self.lead_slots] = host[k][: self.n_cold]
            for slot, hv in plan.hits.items():
                vals[slot] = hv[k]
            for slot, wv in wvals.items():
                vals[slot] = wv[k]
            full[k] = vals[self.inverse]
        failed_rows = None
        if failed:
            failed_rows = np.isin(
                self.inverse, np.fromiter(failed.keys(), np.int64)
            )
        return full, failed_rows, failed


@dataclasses.dataclass
class _WorkItem:
    servable: Servable
    arrays: dict[str, np.ndarray]  # host arrays, candidate-major
    n: int
    future: Future  # resolves to dict[str, np.ndarray]
    enqueue_t: float
    output_keys: tuple[str, ...] | None  # None = all model outputs
    # Absolute perf_counter deadline propagated from the client RPC (None =
    # no client deadline): expired items are shed pre-dispatch.
    deadline_t: float | None = None
    # Warmup work legitimately spends minutes compiling on the batcher
    # thread; it must not read as a wedged device to the circuit breaker.
    warmup: bool = False
    # Per-request tracing handle (utils/tracing.Span of the submitting
    # RPC): the batcher attaches queue-wait + per-phase child spans and
    # fault annotations to it from its own threads. None = untraced.
    span: "tracing.Span | None" = None
    # Criticality lane (overload plane metadata), carried so the quality
    # plane can label its observations per lane. None = unset.
    criticality: str | None = None
    # Streamed sub-batch (ISSUE 9): never coalesced with neighbors — the
    # whole point of the split is that each sub-batch becomes its OWN
    # device batch riding the k-deep pipeline, so its readback (and its
    # chunk flush) completes independently. Coalescing would concatenate
    # the stream right back into the one big batch it was split from.
    solo: bool = False
    # Recovery plane (ISSUE 11): how many times this item has been
    # re-dispatched by the replay path, how many device executors its
    # batches have killed, and — during poisoned-input bisection — the
    # half it belongs to (the coalescer only merges items with EQUAL
    # bisect_key, so a bisected half dispatches as its own batch).
    replays: int = 0
    device_kills: int = 0
    bisect_key: int | None = None
    # Cascade stage-1 prune (ISSUE 19): > 0 asks the jitted entry to
    # return the k best (score, index) survivor pairs plus the stage-1
    # score vector instead of full outputs. Prune submits are forced
    # solo — the survivor indices address the request's own rows.
    prune_k: int = 0


def _replay_group_phases(group: list["_WorkItem"], phases: list) -> None:
    """Attach a batch's collected phase intervals + annotations to every
    traced member request's span (each co-batched request carries the full
    batch timeline — the batch work WAS its work)."""
    if not phases:
        return
    for it in group:
        if it.span is not None:
            tracing.replay_phases(it.span, phases)


@dataclasses.dataclass
class BatcherStats:
    """Occupancy/queueing gauges (SURVEY.md §5 metrics obligations)."""

    batches: int = 0
    requests: int = 0
    candidates: int = 0
    padded_candidates: int = 0
    # Batches assembled by the native fused pack (hostops.cc
    # pack_batch_u24_bf16: fold+u24+bf16+pad+concat in one pass per input
    # instead of 4 python/numpy passes + 3 temporaries).
    fused_batches: int = 0
    # Batches whose outputs rode the top-k compaction (only k (score, idx)
    # pairs crossed the D2H link instead of the full score vector).
    topk_batches: int = 0
    # Cascade stage-1 prune batches (ISSUE 19): the jitted entry returned
    # survivor (score, index) pairs + the wire-dtype stage-1 vector, and
    # the batches where the prune could not arm (needs_x64, custom
    # run_fn, coalesced group) so the orchestrator fell back to a host
    # argpartition over the full score vector.
    prune_batches: int = 0
    prune_fallback_batches: int = 0
    max_queue_depth: int = 0
    # Times coalescing waited past max_wait because the dispatch pipeline
    # was saturated (the wait was latency-free; see _coalesce_next).
    fill_waits: int = 0
    # Intra-batch duplicate collapse (cache/dedup.py): batches whose
    # combined rows held exact duplicates, and how many rows were never
    # padded/uploaded/executed because of it (effective-batch shrink).
    dedup_batches: int = 0
    dedup_rows_collapsed: int = 0
    # Row-granular score cache (cache/row_cache.py, ISSUE 14): batches
    # that went through cold-row extraction, the rows they asked for vs
    # the rows actually dispatched to the device, and batches answered
    # entirely from cache (zero device work). rows_executed ≪
    # rows_requested is the plane's headline claim at zipfian skew.
    row_batches: int = 0
    rows_requested: int = 0
    rows_executed: int = 0
    row_full_hit_batches: int = 0
    # Queued items shed because their propagated client deadline expired
    # before a dispatch slot opened (deadline propagation, ISSUE 2).
    deadline_sheds: int = 0
    # D2H attribution: bytes actually fetched to the host (post-compaction
    # wire dtype, post output filter) vs. what a full-fp32 all-outputs
    # readback of the same batches would have moved.
    bytes_downloaded: int = 0
    bytes_download_full_f32: int = 0
    # Readback overlap: per batch, `window` spans issue->fetch-done and
    # `blocked` is how long the completer actually stalled in the fetch.
    # window==blocked (overlap 0) on the synchronous fallback path.
    readback_window_s: float = 0.0
    readback_blocked_s: float = 0.0
    # Continuous-batching pipeline (ISSUE 9): high-water mark of batches
    # simultaneously in flight (executing or awaiting readback), and how
    # often the dispatch thread waited for the k-deep in-flight window
    # to open before issuing the next batch (inflight_window armed).
    inflight_peak: int = 0
    inflight_window_waits: int = 0

    @property
    def mean_occupancy(self) -> float:
        return self.candidates / self.padded_candidates if self.padded_candidates else 0.0

    @property
    def mean_requests_per_batch(self) -> float:
        return self.requests / self.batches if self.batches else 0.0

    @property
    def readback_overlap_fraction(self) -> float:
        """Fraction of the in-flight D2H window the completer did NOT
        block on — 1.0 means the transfer fully hid behind other work."""
        if self.readback_window_s <= 0:
            return 0.0
        return max(0.0, 1.0 - self.readback_blocked_s / self.readback_window_s)

    @property
    def download_compaction_ratio(self) -> float:
        """full-fp32 baseline bytes / actual downloaded bytes (>=1)."""
        if not self.bytes_downloaded:
            return 0.0
        return self.bytes_download_full_f32 / self.bytes_downloaded


class DynamicBatcher:
    """Queue + batching thread + per-bucket jit cache.

    run_fn(servable, batch) -> outputs is injected so the parallel layer can
    swap in a sharded executor (pjit over a mesh) without touching batching
    logic; the default executes servable.model.apply under jax.jit.
    """

    def __init__(
        self,
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_wait_us: int = 200,
        max_batch_candidates: int | None = None,
        run_fn: Callable | None = None,
        completion_workers: int = 4,
        compress_transfer: bool = True,
        input_cache_entries: int = 64,
        queue_capacity_candidates: int | None = None,
        breaker_timeout_s: float | None = 90.0,
        pipeline_depth: int = 2,
        inflight_window: int = 0,
        buffer_ring: bool = False,
        output_wire_dtype: str = "float32",
        output_top_k: int = 0,
        async_readback: bool = True,
        pipelined_dispatch: bool = True,
        donate_buffers: bool = True,
        score_cache=None,
        row_cache=None,
        dedup: bool = False,
        overload=None,
        utilization=None,
        quality=None,
    ):
        self.compress_transfer = compress_transfer
        # Device-failure recovery plane (serving/recovery.py): a
        # RecoveryController attached post-construction. When set, a
        # device-fatal batch failure hands its work items to the
        # controller for quarantine -> reinit -> replay instead of
        # failing their futures, new submits are refused while the
        # executor rebuilds, and the dispatching/in-flight GROUPS are
        # tracked so a wedge-triggered capture can replay them. None
        # (default) costs one attribute read per hook — the
        # tracing/cache/overload precedent.
        self.recovery = None
        # Data-integrity plane (serving/integrity.py, ISSUE 20): an
        # IntegrityPlane attached post-construction. When set, sampled
        # batches re-execute for a bit-identity shadow compare, delivered
        # score rows pass a post-readback NaN/Inf screen (failing rows
        # fail their OWN request; batchmates deliver), and screen-trip
        # bursts escalate to the recovery cycle. None (default) costs one
        # attribute read per hook — the recovery/quality precedent.
        self.integrity = None
        # Thread-death watchdog (recovery satellite): set to the
        # BatcherThreadDead the moment any batcher-owned thread dies from
        # an unhandled exception; submit() fails fast on it instead of
        # letting submitters hang on the condition variable.
        self._dead: BatcherThreadDead | None = None
        # Model-quality plane (serving/quality.py): a QualityMonitor fed
        # one observe() per completed non-warmup request from _complete —
        # scores are already in host f32 memory post-readback, so the
        # hook costs no device work. Cache hits and brownout stale-serves
        # never reach the completer, so only freshly computed scores are
        # sketched. None (default) costs one attribute read per batch.
        self.quality = quality
        # Kernel plane (ops/autotune.py, ISSUE 12): a KernelManager whose
        # per-bucket decision table routes device execution to the int8
        # weight-quantized params and/or the fused Pallas serving kernel —
        # ONLY where the autotune harness measured a win and the accuracy
        # gates passed. None (default) costs one attribute read per
        # dispatch and behavior is bit-identical to the pre-plane stack.
        self.kernels = None
        # Utilization plane (serving/utilization.py): an OccupancyLedger
        # fed one interval per completed batch from the existing
        # dispatch/readback sites, plus cheap wait-interval records while
        # the batcher idles (the device-idle causes the gap waterfall
        # attributes). None (default) costs one attribute read per hook.
        self.utilization = utilization
        # Overload plane (serving/overload.py): an AdmissionController
        # replaces the static queue_capacity_candidates check with a
        # self-tuning limit, criticality lanes, deadline-aware refusal,
        # and the brownout stale-serve gate. None (default) keeps the
        # static bound and costs one attribute read per submit.
        self.overload = overload
        # Cache plane (cache/): an exact-match ScoreCache short-circuits
        # whole-request repeats at submit (hit = no queue, no device, no
        # dispatch slot; identical concurrent misses single-flight onto one
        # computation), and dedup collapses duplicate rows inside a
        # combined batch before padding/upload. Both off by default; when
        # score_cache is None / dedup False the hot path pays one attribute
        # read per submit/dispatch — the tracing/faults precedent.
        self.score_cache = score_cache
        # Row-granular score cache (cache/row_cache.py, ISSUE 14): after
        # collect, each batch's rows are digested and looked up per row —
        # hot rows answer from cache, ONLY the cold rows are packed,
        # bucketed, and dispatched (possibly a smaller bucket), and the
        # completer scatters device + cached scores back into every
        # request's slice. The whole-request cache above stays in front
        # (a full hit never reaches this plane). None (default) costs one
        # attribute read per batch.
        self.row_cache = row_cache
        self.dedup = bool(dedup)
        # Output-transfer pipeline knobs (utils/config.py ServerConfig
        # carries the same names). wire dtype is validated HERE so a typo'd
        # config fails at construction, not at first dispatch.
        self.output_wire_dtype = output_wire_dtype
        self._wire_dt = _wire_dtype_of(output_wire_dtype)
        self.output_top_k = max(int(output_top_k or 0), 0)
        self.async_readback = async_readback
        self.donate_buffers = donate_buffers
        self._donate_ok: bool | None = None  # resolved lazily (backend init)
        # Content-addressed device-resident inputs (only meaningful for the
        # default jit path; a custom run_fn manages its own placement).
        self.input_cache = (
            DeviceInputCache(input_cache_entries)
            if input_cache_entries and run_fn is None
            else None
        )
        self.buckets = tuple(sorted(buckets))
        self.max_wait_s = max_wait_us / 1e6
        # Clamped: coalescing past the largest bucket would build a batch no
        # bucket can hold and fail the whole group at dispatch time.
        self.max_batch_candidates = min(
            max_batch_candidates or self.buckets[-1], self.buckets[-1]
        )
        # Admission bound: at most this many candidates queued (not yet
        # dispatched). 16 full max-size batches of backlog is already several
        # deadlines' worth of work; past that, shedding with
        # RESOURCE_EXHAUSTED is strictly kinder than queueing.
        # Clamped to at least one full max-size batch: a capacity below
        # buckets[-1] would permanently reject every request larger than it
        # even on an idle queue.
        self.queue_capacity_candidates = max(
            queue_capacity_candidates
            if queue_capacity_candidates is not None
            else 16 * self.buckets[-1],
            self.buckets[-1],
        )
        if self.overload is not None:
            # Resolve the controller's auto limit bounds against this
            # batcher's real geometry (min = one largest bucket, max = the
            # static capacity the controller replaces).
            self.overload.bind(self.buckets[-1], self.queue_capacity_candidates)
        # Wedge threshold for the circuit breaker. Default is above any sane
        # steady-state batch but below the 120s RPC deadline; first compiles
        # belong in warmup(), not live traffic.
        self.breaker_timeout_s = breaker_timeout_s
        # The k-deep continuous-batching window (ISSUE 9). pipeline_depth
        # bounds how many ASSEMBLED groups may be staged ahead of the
        # device stage (the coalescer's free-ride gate reads it too);
        # depth 1 serializes assembly against the device stage (readback
        # still overlaps via the completers) and is allowed but rarely
        # wanted — the historical floor of 2 remains the default.
        self.pipeline_depth = max(pipeline_depth, 1)
        # inflight_window > 0 additionally bounds how many batches may be
        # simultaneously IN FLIGHT (executing or awaiting readback): the
        # dispatch thread keeps issuing batch k+2 while k awaits readback
        # until the window fills, then waits for a completion — deep
        # enough to hide the D2H link, bounded so a slow device cannot
        # accumulate unbounded in-flight HBM. 0 = unbounded (the
        # historical behavior).
        self.inflight_window = max(int(inflight_window or 0), 0)
        # Donation-safe padded-batch buffer reuse; None = allocate fresh
        # per batch (the historical behavior).
        self.buffer_ring = (
            _HostBufferRing(per_key=max(self.inflight_window, 4) + 4)
            if buffer_ring else None
        )
        self._items: "deque[_WorkItem]" = deque()
        self._cv = threading.Condition()
        self._queued_candidates = 0
        # Wedge bookkeeping: wall-clock starts of (a) the device stage
        # currently executing (dispatch thread in pipelined mode, batcher
        # thread otherwise) and (b) every readback in flight.
        self._dispatching_since: float | None = None
        self._inflight: dict[int, float] = {}
        self._inflight_seq = 0
        # Recovery bookkeeping (populated only while a RecoveryController
        # is attached): the group currently in the device stage and the
        # groups executing-or-awaiting-readback, registered/popped at the
        # same _cv sites as the wedge clock so a quarantine capture can
        # replay the EXACT work a wedged device stranded.
        self._dispatching_group: list | None = None
        self._inflight_groups: dict[int, list] = {}
        # Per-bucket in-flight accounting (continuous batching, ISSUE 9):
        # bucket -> batches currently executing-or-awaiting-readback, fed
        # under _cv at the same register/pop sites as _inflight so the
        # two can never disagree. Read by pipeline_stats() and the
        # dts_tpu_pipeline_* Prometheus series.
        self._inflight_buckets: dict[int, int] = {}
        # Pipelined dispatch: groups handed to the dispatch thread but not
        # yet registered in flight. Admission counts their candidates (the
        # queue bound must not weaken just because the pipeline popped
        # them), shedding fails their futures, and _coalesce_next's
        # free-ride gate counts them toward pipeline saturation.
        self.pipelined_dispatch = pipelined_dispatch
        self._dispatcher = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="batch-dispatch")
            if pipelined_dispatch
            else None
        )
        self._dispatch_pending = 0
        self._staged_candidates = 0
        self._staged_groups: dict[int, tuple[list, int]] = {}
        self._staged_seq = 0
        # servable -> [bytes/row of a full-fp32 all-outputs readback],
        # recorded at trace time by the jitted entry (the baseline the
        # bytes_download_full_f32 counter charges).
        self._out_row_bytes: weakref.WeakKeyDictionary[Servable, list] = (
            weakref.WeakKeyDictionary()
        )
        # _jit_for is reached from the batcher thread (fused-path
        # eligibility) AND the dispatch thread; one lock keeps the entry
        # build single-shot.
        self._jit_lock = threading.Lock()
        # Weak keys: unloaded servables must not pin their compiled
        # executables, and a recycled object address must not serve a stale
        # one (Servable uses eq=False, so it is hashable and weakref-able).
        self._jitted: weakref.WeakKeyDictionary[Servable, tuple[Callable, dict]] = (
            weakref.WeakKeyDictionary()
        )
        self._run_fn = run_fn
        self.stats = BatcherStats()
        self._thread = threading.Thread(target=self._loop, name="batcher", daemon=True)
        self._started = False
        self._stopping = False
        # Device->host readback happens off the batching thread so batch k+1's
        # transfer+compute dispatch overlaps batch k's result fetch — this is
        # what pipelines over host<->device link latency (jax dispatch is
        # async; only the fetch blocks). Several workers = several batches'
        # readbacks in flight.
        # Retained for the recovery plane's pool rebuild
        # (replace_workers_for_recovery) — the recovered server must keep
        # this configured readback concurrency.
        self.completion_workers = completion_workers
        self._completers = ThreadPoolExecutor(
            # At least one completer per in-flight-window slot: a window
            # deeper than the pool would leave issued readbacks queued
            # behind completer capacity instead of actually overlapping.
            max_workers=max(completion_workers, self.inflight_window),
            thread_name_prefix="batch-complete",
        )

    # ------------------------------------------------------------------ API

    def start(self) -> "DynamicBatcher":
        if not self._started:
            self._started = True
            self._thread.start()
            # Compile/load the native host ops off-thread so the first
            # request never pays the g++ latency (numpy fallback until ready).
            from .. import native

            native.warm_async()
        return self

    def drain(self, timeout_s: float) -> bool:
        """Block until every accepted item has fully completed — queue
        empty, no staged groups, no dispatch in progress, no readback in
        flight — or `timeout_s` elapses. True = fully drained. The
        graceful-shutdown path (serving/server.py GracefulShutdown) calls
        this AFTER new admissions are refused, so the wait is bounded by
        the work already accepted, not by arriving traffic.

        Recovery interplay (ISSUE 11 satellite): while the recovery plane
        holds captured work (quarantine/reinit/replay in progress), the
        queue can look empty here even though accepted requests are still
        pending replay — the predicate observes the controller's
        cycle_active() so drain neither returns a false True mid-REINIT
        nor deadlocks: the wait stays bounded by `timeout_s` (the
        remaining grace) and GracefulShutdown aborts the cycle first."""
        deadline = time.perf_counter() + max(timeout_s, 0.0)
        rec = self.recovery
        with self._cv:
            while (
                self._items
                or self._staged_groups
                or self._inflight
                or self._dispatch_pending
                or self._dispatching_since is not None
                or (rec is not None and rec.cycle_active())
            ):
                if self._dead is not None:
                    # A dead batching thread will never drain this work;
                    # the waiters were already failed fast.
                    return False
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
        return True

    def stop(self) -> None:
        if self._started:
            with self._cv:
                self._stopping = True
                self._cv.notify_all()
            self._thread.join(timeout=5)
            if self._dispatcher is not None:
                # Every staged group still executes (accepted work is
                # served); the dispatch thread drains before the
                # completers do.
                self._dispatcher.shutdown(wait=True)
            self._completers.shutdown(wait=True)
            self._started = False

    def _wedged_for(self, now: float) -> float:
        """Seconds the oldest stuck batch has been in flight past the
        breaker threshold; 0.0 when healthy. Caller holds _cv."""
        t = self.breaker_timeout_s
        if t is None:
            return 0.0
        worst = 0.0
        if self._dispatching_since is not None:
            worst = now - self._dispatching_since
        for t0 in self._inflight.values():
            worst = max(worst, now - t0)
        return worst if worst > t else 0.0

    def _shed_queued(self, exc: Exception) -> None:
        """Fail every queued (not yet dispatched) item AND every staged
        group still waiting behind the wedged device stage. Caller holds
        _cv."""
        while self._items:
            it = self._items.popleft()
            self._queued_candidates -= it.n
            if not it.future.done():
                it.future.set_exception(exc)
        for sid in list(self._staged_groups):
            group, total = self._staged_groups.pop(sid)
            self._staged_candidates -= total
            for it in group:
                if not it.future.done():
                    it.future.set_exception(exc)

    def submit(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        output_keys: tuple[str, ...] | None = None,
        deadline_s: float | None = None,
        span: "tracing.Span | None" = None,
        criticality: str | None = None,
        _warmup: bool = False,
        _solo: bool = False,
        _prune_k: int = 0,
    ) -> Future:
        """Enqueue one request's arrays; returns a Future of output arrays
        (sliced back to the request's own candidate count). output_keys limits
        which model outputs are fetched back to the host. deadline_s (when
        given) is the CLIENT's remaining budget: an item still queued when it
        expires is shed (RequestDeadlineError -> DEADLINE_EXCEEDED) before
        wasting a dispatch slot. `span` (when per-request tracing is on) is
        the RPC's span handle: the batcher attaches queue-wait and device-
        stage phase child spans to it from its own threads. `criticality`
        (overload plane) picks the admission lane — sheddable traffic is
        refused first under pressure; warmup rides the probe lane.

        Admission control (SURVEY.md §5 failure-detection obligations): a
        wedged device fails the request immediately (DeviceWedgedError, and
        the backlog is shed with it), and a backlog past the admission
        limit — the static queue_capacity_candidates bound, or the
        adaptive overload controller's self-tuned limit when armed — is
        refused (QueueOverloadError / AdmissionRefusedError) instead of
        queueing work no deadline survives.

        _prune_k (cascade stage-1, ISSUE 19): > 0 turns this submit into a
        prune — the result dict carries survivor (score, index) pairs plus
        the stage-1 score vector instead of full outputs. Forced solo
        (survivor indices address the request's own rows), and the score-
        cache key is salted with the mode+k so a prune result can never be
        served to a full-vector request for the same features (or vice
        versa)."""
        if _prune_k:
            _solo = True
        if self._stopping:
            raise RuntimeError("batcher is stopped")
        if self._dead is not None:
            # Thread-death watchdog: a batcher-owned thread died from an
            # unhandled exception — fail fast instead of queueing work
            # nobody will ever dispatch (the recovery plane, when armed,
            # revives the thread and clears this).
            raise self._dead
        ns = {k: v.shape[0] for k, v in arrays.items()}
        n = next(iter(ns.values()))
        if any(v != n for v in ns.values()):
            raise ValueError(f"inconsistent candidate counts across inputs: {ns}")
        bucket_for(n, self.buckets)  # validate size up front, raises if too big
        # Score-cache lookup BEFORE admission: a hit (or a coalesced join
        # onto an identical in-flight miss) bypasses the queue entirely —
        # including the wedge/overload checks, deliberately: cached scores
        # are servable even while the device is wedged or the queue full.
        cache = self.score_cache
        ov = self.overload
        handle = None
        if cache is not None and not _warmup:
            # Brownout stale-serve (overload plane): while pressure is past
            # NOMINAL, an entry up to stale_while_overloaded_s past its TTL
            # still answers — marked degraded, never re-filled — so hot-key
            # traffic keeps getting scores while the device catches up.
            stale_s = (
                ov.stale_window_s
                if ov is not None and ov.stale_serve_active()
                else 0.0
            )
            with request_trace.span("cache.lookup"):
                handle = cache.begin(
                    servable.name, servable.version, output_keys, arrays,
                    stale_s=stale_s,
                    salt=b"prune:%d" % _prune_k if _prune_k else b"",
                )
            if handle.hit is not None:
                if handle.stale:
                    ov.note_brownout_serve()
                    overload_mod.mark_degraded("stale")
                    if span is not None:
                        span.attrs["brownout_stale"] = True
                        span.annotate("overload.stale_serve",
                                      stale_window_s=stale_s)
                elif span is not None:
                    span.attrs["cache_hit"] = True
                fut: Future = Future()
                fut.set_result(handle.hit)
                return fut
            if handle.waiter is not None:
                if span is not None:
                    span.attrs["cache_coalesced"] = True
                return handle.waiter
        try:
            return self._submit_miss(
                servable, arrays, n, output_keys, deadline_s, span, _warmup,
                handle, cache, criticality, _solo, _prune_k,
            )
        except BaseException as exc:
            if handle is not None and handle.leader:
                # The leader never enqueued (admission refused, prepare
                # failed): close the flight so coalesced waiters fail with
                # the same error instead of hanging.
                cache.abort(handle, exc)
            raise

    def _submit_miss(
        self, servable, arrays, n, output_keys, deadline_s, span, _warmup,
        handle, cache=None, criticality=None, solo=False, prune_k=0,
    ) -> Future:
        """The no-cache-hit tail of submit(): admission, prepare, enqueue
        (exactly the pre-cache-plane submit body). The cache handle, when
        this request leads a single-flight, is armed on the future so the
        completion fans out to waiters and fills the cache."""
        # Admission BEFORE the defensive copy: a shed request must not pay
        # the copy/fold cost — overload is exactly when the host can least
        # afford it. Capacity is reserved under the lock so concurrent
        # submits cannot overshoot while this one prepares its arrays.
        ov = self.overload
        rec = self.recovery
        with self._cv:
            if rec is not None and not _warmup and rec.refusing():
                # Quarantine gate (recovery plane): the executor is being
                # torn down/rebuilt — refuse NEW work fast (UNAVAILABLE,
                # clients failover via the scoreboard) while the already-
                # accepted work rides the replay path. Warmup is exempt:
                # the REINIT phase re-warms the bucket ladder through
                # this very queue.
                raise DeviceQuarantinedError(
                    "replica quarantined: device executor is being "
                    f"rebuilt (recovery state {rec.state()}); retry "
                    "against another backend"
                )
            stuck_s = self._wedged_for(time.perf_counter())
            if stuck_s:
                exc = DeviceWedgedError(
                    f"a dispatched batch has been stuck {stuck_s:.1f}s "
                    f"(> breaker {self.breaker_timeout_s:.0f}s); failing fast"
                )
                self._shed_queued(exc)
                raise exc
            backlog = self._queued_candidates + self._staged_candidates
            if ov is not None:
                # Adaptive admission: self-tuned limit + criticality lane
                # + doomed-work refusal, with a retry-after pushback hint
                # on every refusal (serving/overload.py).
                lane = (
                    overload_mod.PROBE if _warmup
                    else overload_mod.normalize_criticality(criticality)
                )
                decision = ov.admit(n, backlog, lane=lane, deadline_s=deadline_s)
                if not decision.admitted:
                    if span is not None:
                        span.annotate(
                            "overload.shed", reason=decision.reason,
                            lane=lane, retry_after_ms=decision.retry_after_ms,
                        )
                    if (util := self.utilization) is not None:
                        # Gap-attribution event: an empty queue during a
                        # shed storm is refused traffic, not absent
                        # traffic (idle cause "admission_shed").
                        util.note_shed()
                    raise AdmissionRefusedError(
                        decision.message,
                        reason=decision.reason or "shed",
                        retry_after_ms=decision.retry_after_ms,
                    )
            elif backlog + n > self.queue_capacity_candidates:
                if (util := self.utilization) is not None:
                    util.note_shed()
                raise QueueOverloadError(
                    f"queue holds {backlog} candidates (queued + staged); "
                    f"admitting {n} more would exceed capacity "
                    f"{self.queue_capacity_candidates}"
                )
            self._queued_candidates += n
        fut: Future = Future()
        try:
            now = time.perf_counter()
            item = _WorkItem(
                servable=servable,
                arrays=prepare_inputs(servable.model, arrays, fold_ids=False),
                n=n,
                future=fut,
                enqueue_t=now,
                output_keys=output_keys,
                deadline_t=(now + deadline_s) if deadline_s is not None else None,
                warmup=_warmup,
                span=span if tracing.enabled() else None,
                criticality=criticality,
                solo=solo,
                prune_k=prune_k,
            )
        except BaseException:
            with self._cv:
                self._queued_candidates -= n
            raise
        with self._cv:
            self._items.append(item)
            self.stats.max_queue_depth = max(self.stats.max_queue_depth, len(self._items))
            self._cv.notify()
        if handle is not None and handle.leader:
            # Fill + waiter fan-out ride the future's completion (success,
            # failure, or cancellation), on whichever thread resolves it.
            # `cache` is the instance that MINTED the handle in submit()
            # (passed down, never re-read from self here): detaching or
            # swapping score_cache with leaders in flight (bench A/B
            # teardown) must still close those leaders' flights, or their
            # coalesced waiters hang. The leader's servable/arrays ride
            # along so a deadline-killed leader's waiters can be
            # re-dispatched instead of inheriting its deadline fate.
            fut.add_done_callback(
                lambda f, h=handle, c=cache, sv=servable, a=arrays,
                ok=output_keys, pk=prune_k:
                self._cache_complete(c, h, f, sv, a, ok, pk)
            )
        return fut

    def _cache_complete(
        self, cache, handle, fut: Future, servable, arrays, output_keys,
        prune_k: int = 0,
    ) -> None:
        """Close a single-flight leader's computation into the cache:
        successful results fill (and wake coalesced waiters), failures fan
        out. A leader killed by ITS OWN deadline (service-timeout cancel,
        queued-deadline shed) does not doom its waiters — their budgets are
        their own, so the computation is re-dispatched once on their
        behalf (deadline-free; a fresh identical request would coalesce
        onto it). Runs as a Future done-callback on a completer/service
        thread."""
        deadline_shaped = fut.cancelled() or isinstance(
            fut.exception(), RequestDeadlineError
        )
        if deadline_shaped:
            waiters = [
                w for w in cache.take_waiters(handle) if not w.cancelled()
            ]
            if not waiters:
                return
            try:
                retry = self.submit(
                    servable, arrays, output_keys=output_keys,
                    _prune_k=prune_k,
                )
            except BaseException as exc:  # stopped/wedged/overloaded batcher
                for w in waiters:
                    try:
                        w.set_exception(exc)
                    except InvalidStateError:
                        pass
                return

            def chain(rf: Future) -> None:
                for w in waiters:
                    if w.cancelled():
                        continue
                    try:
                        if rf.cancelled():
                            w.cancel()
                        elif rf.exception() is not None:
                            w.set_exception(rf.exception())
                        else:
                            w.set_result(rf.result())
                    except InvalidStateError:
                        pass

            retry.add_done_callback(chain)
            return
        degraded = getattr(fut, "dts_degraded", None)
        if degraded is not None:
            # The leader's response was assembled with brownout-STALE row
            # entries (row plane, ISSUE 14): it must never fill the
            # whole-request cache — a fresh-TTL entry would keep serving
            # past-TTL data unmarked long after the brownout clears — and
            # every coalesced waiter inherits the degraded marker with
            # the result (the service forwards it per future).
            waiters = cache.take_waiters(handle)
            if waiters:
                result = fut.result()
                for w in waiters:
                    if w.cancelled():
                        continue
                    w.dts_degraded = degraded
                    try:
                        w.set_result(result)
                    except InvalidStateError:
                        pass
            return
        with request_trace.span("cache.fill"):
            cache.complete(handle, fut)

    @staticmethod
    def warmup_arrays(servable: Servable, n: int) -> dict[str, np.ndarray]:
        """Zero batch matching the servable's default-signature inputs —
        signature-driven so optional inputs (DLRM dense_features) are
        included and imported signatures warm what they actually declare."""
        from .. import codec

        sig = servable.signature("")
        out = {}
        for spec in sig.inputs:
            if spec.shape is None or len(spec.shape) < 1:
                continue  # unknown rank: nothing sensible to synthesize
            dims = (n,) + tuple(d or 1 for d in spec.shape[1:])
            out[spec.name] = np.zeros(dims, codec.dtype_to_numpy(spec.dtype))
        return out

    def warmup(self, servable: Servable, buckets: tuple[int, ...] | None = None) -> None:
        """Precompile the bucket ladder for a servable (compile storms belong
        at load time, not first-request time). Executes directly — only safe
        before the batcher serves traffic; once live, use warmup_via_queue.
        EXCEPTION: elastic run_fns — the elastic branch below routes through
        warmup_call into each ShardedExecutor's internally-locked entry
        cache and never touches the single-chip _jitted dict this contract
        protects, so warmup_via_queue's ladder tail and the recovery
        re-warm deliberately call it on a LIVE batcher. Keep it that way:
        batcher-level warmup state for run_fn executors belongs behind
        the queue, not here.

        Each bucket warms the output-selection variants live traffic
        predictably hits: the all-outputs entry (unfiltered requests,
        direct submits), the score-only entry (output_filter'd requests —
        the reference client filters to its output_key), the top-k entry
        when configured (its queue-path gate skips warmup items, so ONLY
        this direct pass can precompile it — a live compile on the dispatch
        path would stall the pipeline with the wedge clock armed), and the
        donating variant of each where buffer donation is effective
        (cache-bypass traffic compiles a distinct executable; its first
        batch must not pay the compile). A client filtering to any OTHER
        output subset still compiles its variant at first request — rare
        enough (subsets of the signature's outputs) that warming the
        combinatorial space is not worth the load-time."""
        model = servable.model
        if self._run_fn is not None:
            # Custom executors ignore donate/topk — but an executor that
            # honors output selection (the mesh path's supports_out_keys)
            # compiles a distinct executable per out_keys, so both
            # variants live traffic predictably hits (all-outputs +
            # score-only) warm here; other executors get the historical
            # one execution per bucket.
            out_variants: tuple = (None,)
            if getattr(self._run_fn, "supports_out_keys", False):
                out_variants = (None, (model.score_output,))
            # Elastic executors warm EVERY split's executable per variant
            # (warmup_call) — the switch-never-compiles contract: a
            # runtime split change must never pay an XLA compile on the
            # dispatch path (which would stall the pipeline, and trip the
            # [recovery] wedge clock when armed). The arrays are folded
            # here exactly like _execute folds them, so the warmed
            # executables match live traffic's dtypes.
            warm_all = (
                self._run_fn.warmup_call
                if getattr(self._run_fn, "elastic", False) else None
            )
            for b in buckets or self.buckets:
                arrays = prepare_inputs(model, self.warmup_arrays(servable, b))
                for out_keys in out_variants:
                    if warm_all is not None:
                        warm_all(
                            servable, self._fold_host(servable, arrays),
                            out_keys=out_keys,
                        )
                    else:
                        self._execute(servable, arrays, out_keys=out_keys)
            return
        score_only = (model.score_output,)
        _, _, combined = self._jit_for(servable)
        for b in buckets or self.buckets:
            arrays = prepare_inputs(model, self.warmup_arrays(servable, b))
            for out_keys in (None, score_only):
                self._execute(servable, arrays, out_keys=out_keys)
                if combined and self._donation_ok():
                    # Only combined entries HAVE a donating variant; the
                    # per-key path ignores donate, and re-running it would
                    # just double warmup time for the slowest (x64) models.
                    self._execute(
                        servable, arrays, out_keys=out_keys, _force_donate=True
                    )
            if (
                self.output_top_k
                and self._run_fn is None
                and not model.needs_x64
                and self.output_top_k < b
            ):
                self._execute(
                    servable, arrays, out_keys=score_only,
                    topk=self.output_top_k, n_valid=b,
                )

    def warmup_via_queue(
        self, servable: Servable, buckets: tuple[int, ...] | None = None
    ) -> None:
        """Warm a servable THROUGH the request queue: compilation happens on
        the batching thread exactly like live traffic, so hot-loading a new
        model version never races the jit caches with in-flight requests."""
        futures = [
            self.submit(servable, self.warmup_arrays(servable, b), _warmup=True)
            for b in buckets or self.buckets
        ]
        for fut in futures:
            fut.result(timeout=600)
        if getattr(self._run_fn, "elastic", False):
            # The queue path compiled only the CURRENT split's entries.
            # Warm the rest of the ladder directly (warmup() routes
            # elastic run_fns through warmup_call — every split; the
            # current split's second pass is a cache hit), so a
            # hot-loaded version keeps the switch-never-compiles
            # contract: its first post-switch batch must not pay an XLA
            # compile on the dispatch path.
            self.warmup(servable, buckets)

    def jit_entry(self, servable: Servable) -> tuple[Callable, dict[str, str], bool]:
        """The (jitted fn, transfer spec, combined) this batcher serves
        `servable` with — public so measurement harnesses (bench.py's
        device-limited decomposition) can time the EXACT serving executable,
        warm caches included, instead of compiling a lookalike. When
        `combined` is True the fn signature is (params, uint8_buffer,
        layout) with layout static (ops/transfer.py combined_layout); both
        shapes accept optional keywords (out_keys, donate, topk, n_valid)
        selecting the output-compaction variant — defaults reproduce the
        all-outputs entry (see _build_entry)."""
        return self._jit_for(servable)

    def queue_load(self) -> tuple[int, int]:
        """(queued + staged candidates, configured queue capacity) — the
        elastic controller's queue-pressure signal (parallel/elastic.py):
        the fraction of the admission bound currently waiting is the
        backlog term of its load EWMA. One lock hold, called at most once
        per controller tick interval."""
        with self._cv:
            return (
                self._queued_candidates + self._staged_candidates,
                self.queue_capacity_candidates,
            )

    def pipeline_stats(self) -> dict:
        """Continuous-batching pipeline snapshot (ISSUE 9): configured
        depth/window, live in-flight occupancy (total and per bucket),
        high-water marks, and the readback-overlap fraction — the body of
        the /monitoring `pipeline` block and the dts_tpu_pipeline_*
        Prometheus series. Always available (core batcher state, not a
        gated plane)."""
        with self._cv:
            in_flight = len(self._inflight)
            dispatching = self._dispatching_since is not None
            per_bucket = {
                int(b): n for b, n in sorted(self._inflight_buckets.items())
                if n
            }
            pending = self._dispatch_pending
            peak = self.stats.inflight_peak
            window_waits = self.stats.inflight_window_waits
            overlap = self.stats.readback_overlap_fraction
        out = {
            "depth": self.pipeline_depth,
            "inflight_window": self.inflight_window,
            "in_flight": in_flight,
            "dispatching": dispatching,
            "dispatch_pending": pending,
            "per_bucket_in_flight": per_bucket,
            "inflight_peak": peak,
            "inflight_window_waits": window_waits,
            "readback_overlap_fraction": round(overlap, 4),
        }
        if self.buffer_ring is not None:
            out["buffer_ring"] = self.buffer_ring.snapshot()
        return out

    # ------------------------------------------- recovery plane (ISSUE 11)

    def wedge_age(self) -> float:
        """Seconds the OLDEST dispatched-or-in-flight batch has been
        outstanding (0.0 when idle/healthy) — the raw wedge clock the
        recovery watchdog escalates into a quarantine decision at its own
        (usually much lower) threshold, independent of the circuit
        breaker's fail-fast bound."""
        with self._cv:
            now = time.perf_counter()
            worst = 0.0
            if self._dispatching_since is not None:
                worst = now - self._dispatching_since
            for t0 in self._inflight.values():
                worst = max(worst, now - t0)
            return worst

    def capture_for_recovery(self) -> tuple[list, list]:
        """Quarantine capture: pop EVERY accepted-but-unanswered work item
        out of the batcher — queued items, staged groups, the group in the
        device stage, and every group executing-or-awaiting-readback — and
        clear the wedge bookkeeping so the rebuilt executor starts with a
        clean clock. Returns (queued_items, inflight_groups): queued items
        were never in a failing device call (replayed without a kill
        mark), in-flight groups were (the wedge IS their kill evidence).

        Safe against the stranded threads by construction: a wedged stage
        call whose sid was popped no-ops when it eventually runs, a stuck
        readback that eventually completes resolves futures the replay
        already resolved (set_result is first-wins, InvalidStateError
        guarded), and the pending-count decrements clamp at zero."""
        with self._cv:
            queued: list[_WorkItem] = []
            while self._items:
                it = self._items.popleft()
                self._queued_candidates -= it.n
                if not it.future.done():
                    queued.append(it)
            for sid in list(self._staged_groups):
                group, total = self._staged_groups.pop(sid)
                self._staged_candidates -= total
                queued.extend(it for it in group if not it.future.done())
            inflight: list[list[_WorkItem]] = []
            if self._dispatching_group is not None:
                live = [
                    it for it in self._dispatching_group
                    if not it.future.done()
                ]
                if live:
                    inflight.append(live)
                self._dispatching_group = None
            for group in self._inflight_groups.values():
                live = [it for it in group if not it.future.done()]
                if live:
                    inflight.append(live)
            self._inflight_groups.clear()
            self._inflight.clear()
            self._inflight_buckets.clear()
            self._dispatching_since = None
            self._dispatch_pending = 0
            self._cv.notify_all()
        rc = self.row_cache
        if rc is not None:
            # Close EVERY in-flight row fill: the leaders of these flights
            # may be stranded in wedged threads the pool replacement
            # abandons (never unwinding through the abort paths), and a
            # foreign — or future — batch joining such a zombie flight
            # would hang to its deadline on a fill that can never land.
            # Replayed batches re-plan their rows fresh; the failed
            # waiters' clients failover on UNAVAILABLE like any
            # quarantine refusal.
            rc.fail_flights(DeviceQuarantinedError(
                "replica quarantined: in-flight row fills abandoned "
                "(the replayed batches re-plan their rows)"
            ))
        return queued, inflight

    def requeue_for_replay(self, items: list) -> None:
        """Re-enqueue captured/failed items at the FRONT of the queue (the
        replay path; they were accepted before anything now queued).
        Admission is deliberately bypassed — this work was already
        admitted once — and enqueue_t restarts so replay queue-wait is
        charged to the replay, while the propagated client deadline rides
        along unchanged (a waiter that gave up mid-recovery is shed
        exactly like any expired item)."""
        now = time.perf_counter()
        with self._cv:
            for it in reversed(items):
                it.enqueue_t = now
                self._items.appendleft(it)
                self._queued_candidates += it.n
            self._cv.notify_all()

    def replace_workers_for_recovery(self) -> None:
        """Abandon the dispatch/completer pools (a thread wedged inside a
        native device call cannot be preempted in-process — the pool
        around it can) and mint fresh ones so REPLAY has live workers.
        The old pools shut down without waiting: their idle threads exit,
        a stranded one finishes (or never does) against bookkeeping that
        capture_for_recovery already reset."""
        old_dispatcher, old_completers = self._dispatcher, self._completers
        if self._dispatcher is not None:
            self._dispatcher = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="batch-dispatch"
            )
        self._completers = ThreadPoolExecutor(
            # The constructor's sizing rule, not a hardcoded floor: a
            # recovered server must keep its configured readback
            # concurrency.
            max_workers=max(self.completion_workers, self.inflight_window),
            thread_name_prefix="batch-complete",
        )
        for pool in (old_dispatcher, old_completers):
            if pool is not None:
                pool.shutdown(wait=False)

    def revive_batching_thread(self) -> bool:
        """Clear a thread-death verdict and restart the batching loop if
        it is gone (recovery REINIT). True when a restart happened. The
        dying thread reports its own death BEFORE its final frames
        unwind, so on a RECORDED death a brief join lets it actually exit
        — without it the is_alive() check would read the corpse as a
        live loop. No death recorded = no join: a healthy loop blocked
        in _take must not add a fixed stall to every recovery cycle."""
        with self._cv:
            died = self._dead is not None
            self._dead = None
        t = self._thread
        if died and t.is_alive() and t is not threading.current_thread():
            t.join(timeout=2.0)
        if (
            self._started
            and not self._stopping
            and not self._thread.is_alive()
        ):
            self._thread = threading.Thread(
                target=self._loop, name="batcher", daemon=True
            )
            self._thread.start()
            return True
        return False

    def _note_thread_death(self, which: str, exc: BaseException) -> None:
        """A batcher-owned thread died from an unhandled exception: record
        the verdict so submit() fails fast, fail everything queued (no
        recovery plane) or hand the death to the recovery plane (armed —
        it revives the thread and replays), and wake every waiter."""
        err = BatcherThreadDead(
            f"batcher {which} thread died: {type(exc).__name__}: {exc}"
        )
        err.__cause__ = exc
        rec = self.recovery
        with self._cv:
            first = self._dead is None
            if first:
                self._dead = err
            self._cv.notify_all()
        # Hand the death to the recovery plane ONLY if it accepts it (a
        # stopped controller — drain in progress — returns False): queued
        # waiters are either replayed by the cycle or failed fast here,
        # never left hanging between the two.
        handled = rec is not None and first and rec.note_thread_death(err)
        if first and not handled:
            with self._cv:
                self._shed_queued(err)
                self._cv.notify_all()

    def _guard_worker_future(self, fut: Future, group: list, which: str) -> None:
        """Done-callback on dispatch/completer pool submissions: the stage
        bodies catch Exception, so anything surfacing HERE is an escape
        (BaseException, a bug in a finally) that would otherwise strand
        the group's waiters silently. Fail them fast and record the
        death."""
        exc = fut.exception()
        if exc is None:
            return
        for it in group:
            if not it.future.done():
                try:
                    it.future.set_exception(
                        BatcherThreadDead(
                            f"batcher {which} worker died: "
                            f"{type(exc).__name__}: {exc}"
                        )
                    )
                except InvalidStateError:
                    pass
        self._note_thread_death(which, exc)

    # ------------------------------------------------------------- internals

    def _donation_ok(self) -> bool:
        """Buffer donation is effective only off-CPU (the CPU backend
        ignores it with a warning per call) and only when enabled.
        Resolved lazily so constructing a batcher never forces backend
        init."""
        if self._donate_ok is None:
            self._donate_ok = (
                self.donate_buffers and jax.default_backend() != "cpu"
            )
        return self._donate_ok

    def _jit_for(self, servable: Servable) -> tuple[Callable, dict[str, str], bool]:
        with self._jit_lock:
            entry = self._jitted.get(servable)
            if entry is None:
                combined = self.compress_transfer and not servable.model.needs_x64
                entry = self._build_entry(servable, combined)
                self._jitted[servable] = entry
        return entry

    def _build_entry(
        self, servable: Servable, combined: bool
    ) -> tuple[Callable, dict[str, str], bool]:
        """One callable serving every executable variant for `servable`.

        The returned fn accepts optional keywords beyond the positional
        (params, inputs[, layout]) contract jit_entry publishes:

        - out_keys: hashable tuple restricting which model outputs the
          EXECUTABLE returns (None = all). Dead outputs are DCE'd by XLA
          and never materialize in HBM, let alone cross the D2H link.
        - donate: donate the combined input buffer's HBM to the executable
          (single-use buffers only — never cache-resident ones).
        - topk/n_valid: top-k output compaction — only the k best
          (score, index) pairs of the first n_valid rows come back.
          n_valid is traced, so executables key on (bucket, k) alone.

        Each distinct (layout, out_keys, donate, topk) is a separate jit
        closure, cached here exactly like the old per-layout cache; the
        inner jax.jit trace cache still keys on buffer shape. The variant
        count is bounded by the distinct output_filter subsets clients
        actually send (the service validates filters against the signature,
        so the space is subsets of the signature's outputs — a handful),
        not by traffic volume. All float32 outputs are downcast to the
        configured wire dtype on-device, and the full-fp32 row bytes are
        recorded at trace time so the bytes_download_full_f32 counter
        charges an honest baseline.
        """
        model = servable.model
        spec = transfer_spec(model) if self.compress_transfer else {}
        apply = model.apply
        # x64 graphs may carry f64 outputs whose downcast would not be a
        # transparent wire encoding; they keep full-precision outputs.
        wire = None if model.needs_x64 else self._wire_dt
        score_key = model.score_output
        rowbytes = self._out_row_bytes.setdefault(servable, [0])

        def finish(out, out_keys):
            # Runs at TRACE time: record the full-fp32 readback baseline
            # for this servable (bytes/row across ALL outputs), then apply
            # output selection + the on-device wire downcast.
            n = next(iter(out.values())).shape[0]
            rb = 0
            for v in out.values():
                per_row = max(int(np.prod(v.shape)) // max(n, 1), 1)
                width = 4 if jnp.issubdtype(v.dtype, jnp.floating) else v.dtype.itemsize
                rb += per_row * width
            rowbytes[0] = max(rowbytes[0], rb)
            if out_keys is not None:
                picked = {k: v for k, v in out.items() if k in out_keys}
                out = picked or out  # never trace an empty output pytree
            return compact_outputs_device(out, wire)

        variants: dict[tuple, Callable] = {}

        if combined:
            # One uint8 buffer per batch = ONE host->device transfer
            # instead of one per input; the layout split + bitcasts are
            # traced into the executable and fuse with consumers.
            # (x64 models keep the per-key path: their int64 inputs
            # must cross the boundary as int64, not raw bytes plus an
            # in-graph bitcast that enable_x64 scoping complicates.)
            #
            # The layout is CLOSED OVER per distinct variant key (a
            # handful per servable — bucket-independent metadata) instead
            # of riding static_argnums: hashing that nested tuple on
            # every call cost ~175 us/batch of pure dispatch overhead
            # (round-4 microbench: 426 -> 251 us/call arg processing),
            # and the inner jit cache keys on buffer shape exactly as
            # before.
            def fn(
                params, buf, layout, out_keys=None, donate=False,
                topk=0, n_valid=None, k_apply=None, prune=False,
                _cache=variants,
            ):
                # k_apply (kernel plane, ISSUE 12): an alternate apply
                # callable — the fused Pallas serving kernel — swapped in
                # per the per-bucket autotune decision. Its identity joins
                # the variant key so the Pallas and XLA executables
                # coexist; quantized params need no key (jax.jit retraces
                # on the distinct param-tree structure).
                key = (layout, out_keys, donate, topk, k_apply, prune)
                jfn = _cache.get(key)
                if jfn is None:
                    donargs = (1,) if donate else ()
                    ap = k_apply or apply
                    if topk:
                        select = cascade_prune_device if prune \
                            else topk_compact_device
                        def run(p, b, nv, _l=layout, _k=topk, _ap=ap,
                                _sel=select):
                            out = _ap(p, unpack_device_combined(b, _l))
                            finish(out, None)  # records the baseline
                            return _sel(out[score_key], nv, _k, wire)
                    else:
                        def run(p, b, _l=layout, _ok=out_keys, _ap=ap):
                            return finish(_ap(p, unpack_device_combined(b, _l)), _ok)
                    jfn = _cache[key] = jax.jit(run, donate_argnums=donargs)
                return jfn(params, buf, n_valid) if topk else jfn(params, buf)
        else:
            def fn(
                params, packed, out_keys=None, donate=False,
                topk=0, n_valid=None, k_apply=None, prune=False,
                _cache=variants,
            ):
                key = (out_keys, topk, k_apply, prune)
                jfn = _cache.get(key)
                if jfn is None:
                    ap = k_apply or apply
                    if topk:
                        select = cascade_prune_device if prune \
                            else topk_compact_device
                        def run(p, b, nv, _k=topk, _ap=ap, _sel=select):
                            batch = unpack_device(b, spec) if spec else b
                            out = _ap(p, batch)
                            finish(out, None)
                            return _sel(out[score_key], nv, _k, wire)
                    else:
                        def run(p, b, _ok=out_keys, _ap=ap):
                            # Transfer decompression is traced into the
                            # executable, so it fuses with the embedding
                            # lookup's index arithmetic.
                            batch = unpack_device(b, spec) if spec else b
                            return finish(_ap(p, batch), _ok)
                    jfn = _cache[key] = jax.jit(run)
                return jfn(params, packed, n_valid) if topk else jfn(params, packed)

        if model.needs_x64:
            # Trace AND call inside enable_x64: graph-executor models
            # (interop/graph_exec.py) carry int64 feature ids that the
            # default 32-bit canonicalization would silently truncate at
            # the jit boundary — before the graph's own hashing/mod runs.
            base = fn

            def fn(params, batch, *args, _base=base, **kwargs):
                with enable_x64():
                    return _base(params, batch, *args, **kwargs)

        return (fn, spec, combined)

    _FUSED_SPEC = {"feat_ids": "u24", "feat_wts": "bf16"}

    def _fused_ctx(self, group: list[_WorkItem], bucket: int) -> dict | None:
        """Eligibility + host-side metadata for the native fused batch
        assembler; None = the generic pad+pack path runs instead. Pure
        bookkeeping (no device work), so it runs on the batcher thread —
        the device stage itself (_execute_fused) rides the dispatch
        pipeline.

        hostops.cc pack_batch_u24_bf16 reads each request's arrays once and
        writes the final padded [u24 ids | bf16 wts] device buffer directly
        — the generic path makes 4 full passes (pad copy, fold, pack,
        concat) with 3 temporaries per batch (~1.25 ms/batch at the 16k
        bucket on this host, round-3 phases). The buffer is bit-identical
        to pack_host_combined over the padded batch (pinned by
        tests/test_batcher.py), so it shares the same compiled executables
        and the same content-cache semantics (keyed per-part here; distinct
        tag keeps the two key schemes apart)."""
        import os

        import ml_dtypes

        from .. import native

        servable = group[0].servable
        model = servable.model
        if (
            self._run_fn is not None
            or not self.compress_transfer
            or model.needs_x64
            or not model.folds_ids_on_host
            or os.environ.get("DTS_TPU_NO_FUSED") == "1"  # A/B isolation knob
            or not native.available()
        ):
            return None
        fn, spec, combined = self._jit_for(servable)
        if not combined or spec != self._FUSED_SPEC:
            return None
        first = group[0].arrays
        if set(first) != {"feat_ids", "feat_wts"}:
            return None
        fields = first["feat_ids"].shape[1] if first["feat_ids"].ndim == 2 else None
        if not fields:
            return None
        for it in group:
            ids, wts = it.arrays["feat_ids"], it.arrays["feat_wts"]
            if (
                ids.ndim != 2 or ids.shape[1] != fields
                or wts.shape != ids.shape
                or ids.dtype not in (np.int64, np.int32)
                or wts.dtype not in (np.float32, ml_dtypes.bfloat16)
            ):
                return None
        layout = combined_layout(
            {k: first[k] for k in ("feat_ids", "feat_wts")}, spec
        )
        return {
            "servable": servable,
            "fn": fn,
            "layout": layout,
            "vocab": model.config.vocab_size,
            "fields": fields,
            "ids_parts": [it.arrays["feat_ids"] for it in group],
            "wts_parts": [it.arrays["feat_wts"] for it in group],
        }

    def _execute_fused(
        self, ctx: dict, bucket: int,
        out_keys: tuple[str, ...] | None, topk: int, n_valid,
        prune: bool = False,
    ):
        """Device stage of the fused path: content cache / native pack /
        upload / jit call (cache+pack+jitcall spans match the generic
        path's, so fused/generic phase decompositions compare like for
        like)."""
        from .. import native

        servable, fn, layout = ctx["servable"], ctx["fn"], ctx["layout"]
        vocab, fields = ctx["vocab"], ctx["fields"]
        ids_parts, wts_parts = ctx["ids_parts"], ctx["wts_parts"]

        def build():
            return native.pack_batch_u24_bf16(
                ids_parts, wts_parts, fields, bucket, vocab
            )

        cache = self.input_cache
        if cache is not None and not cache.bypassed:
            with request_trace.span("batch.cache"):
                # Per-part content digests (same digest primitive, same
                # total bytes as the group digest) + padded geometry.
                # vocab is IN the tag: the digests are over RAW ids,
                # and the stored buffer's fold depends on it — two
                # servables sharing a batcher but not a vocab must
                # never share entries (review finding; the generic
                # path's digests are post-fold so it gets this free).
                key = (
                    (f"fused:{layout}:{bucket}:{vocab}",)
                    + tuple(cache._key("i", a) for a in ids_parts)
                    + tuple(cache._key("w", a) for a in wts_parts)
                )
                buf = cache._lookup(key, build)
        else:
            if cache is not None:
                cache._note_bypassed()
            with request_trace.span("batch.fusedpack"):
                buf = build()
        # Donate only single-use buffers: a cache-resident device array's
        # HBM must survive this call for the next content hit. Cache-held
        # buffers are jax.Arrays; only a bypass/no-cache build hands back
        # the single-use host buffer.
        donate = isinstance(buf, np.ndarray) and self._donation_ok()
        # np.int32, matching _execute and warmup(): a raw Python int has a
        # different jax aval (weak type) and would force a fresh trace on
        # the first live fused top-k batch despite warmup's precompile.
        n_valid = None if not topk else np.int32(n_valid)
        # Kernel plane: the fused native assembler and the kernel variants
        # compose — the packed buffer is variant-independent input bytes.
        k_params, k_apply = self._kernel_variant(servable, bucket)
        with request_trace.span("batch.jitcall"):
            return fn(
                k_params, buf, layout,
                out_keys=out_keys, donate=donate, topk=topk, n_valid=n_valid,
                k_apply=k_apply, prune=prune,
            )

    def _kernel_variant(self, servable: Servable, rows: int, override=None):
        """(params, k_apply) per the kernel plane's per-bucket decision —
        the int8-quantized param tree and/or the fused Pallas serving
        apply — or (servable.params, None) for the baseline. `override`
        is the autotune harness's (quantized, pallas) pin, so measurement
        runs through the EXACT entry (and jit cache) live traffic uses."""
        kern = self.kernels
        if kern is None or self._run_fn is not None:
            return servable.params, None
        dec = override if override is not None else kern.decision(servable, rows)
        if not dec or dec == (False, False):
            return servable.params, None
        quantized, pallas = dec
        params = (
            kern.params_for(servable, True) if quantized else servable.params
        )
        k_apply = kern.pallas_apply_for(servable, quantized) if pallas else None
        return params, k_apply

    @staticmethod
    def _fold_host(servable: Servable, arrays: dict) -> dict:
        """Deferred per-request fold (prepare_inputs fold_ids=False): one
        native fold over the whole padded batch. Runs BEFORE the content
        digest, so cache keys are over the same folded bytes as the
        eager-fold path produced. Shared by _execute and the elastic
        warmup path (which calls the run_fn directly and must hand it the
        exact dtype live traffic carries — an unfolded int64 batch would
        warm an executable no live batch ever hits)."""
        ids = arrays.get("feat_ids")
        if ids is not None and ids.dtype == np.int64 and servable.model.folds_ids_on_host:
            arrays = dict(arrays)
            arrays["feat_ids"] = fold_ids_host(ids, servable.model.config.vocab_size)
        return arrays

    def _execute(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        out_keys: tuple[str, ...] | None = None,
        topk: int = 0,
        n_valid: int | None = None,
        prune: bool = False,
        _force_donate: bool = False,
        _kernel_override=None,
    ):
        """Device stage for one padded batch: fold, content cache, pack,
        upload, jit call. out_keys/topk/n_valid ride through to the jitted
        entry (output selection and top-k compaction are traced into the
        executable); _force_donate is the warmup hook that precompiles the
        donating variant without going through cache-bypass traffic;
        _kernel_override pins the kernel plane's (quantized, pallas)
        variant for the autotune harness."""
        arrays = self._fold_host(servable, arrays)
        if self._run_fn is not None:
            if getattr(self._run_fn, "supports_out_keys", False):
                # Mesh executor (parallel/executor.py): the group's
                # output-selection union rides through so unwanted outputs
                # are DCE'd on-mesh and never cross the gathered D2H link
                # — the same PR-1 compaction the single-chip entries get.
                return self._run_fn(servable, arrays, out_keys=out_keys)
            return self._run_fn(servable, arrays)
        k_params, k_apply = self._kernel_variant(
            servable, next(iter(arrays.values())).shape[0], _kernel_override
        )
        fn, spec, combined = self._jit_for(servable)
        if combined and not combined_supported(arrays):
            # Rare servable whose inputs cannot ride a byte buffer (string/
            # bool/8-byte tensors): rebuild the per-key entry once and pin
            # it (same spec — only the transfer packaging changes).
            with self._jit_lock:
                entry = self._build_entry(servable, combined=False)
                self._jitted[servable] = entry
            fn, spec, combined = entry
        n_valid = None if not topk else np.int32(n_valid)
        # x64 models need the context around the UPLOADS too: device_put
        # (inside the input cache) canonicalizes, and an int64 batch put
        # outside the context reaches the x64-traced executable as int32.
        ctx = enable_x64() if servable.model.needs_x64 else _NULL_CTX
        with ctx:
            if combined:
                layout = combined_layout(arrays, spec)
                cache = None if _force_donate else self.input_cache
                if cache is not None:
                    # Digest the RAW arrays (a content hit skips pack AND
                    # concat AND upload); layout in the tag keeps distinct
                    # packings of identical bytes apart.
                    with request_trace.span("batch.cache"):
                        buf = cache.get_or_put_group(
                            arrays,
                            build=lambda: pack_host_combined(arrays, spec),
                            tag=str(layout),
                        )
                    # A cache-resident device buffer must never be donated
                    # (its HBM has to survive for the next content hit);
                    # bypass-mode lookups hand back the single-use HOST
                    # buffer, which is safe to donate.
                    donate = isinstance(buf, np.ndarray) and self._donation_ok()
                else:
                    buf = pack_host_combined(arrays, spec)
                    donate = _force_donate or self._donation_ok()
                with request_trace.span("batch.jitcall"):
                    return fn(
                        k_params, buf, layout,
                        out_keys=out_keys, donate=donate,
                        topk=topk, n_valid=n_valid, k_apply=k_apply,
                        prune=prune,
                    )
            if self.input_cache is not None and not _force_donate:
                # Digest BEFORE packing: a content hit skips both the upload
                # and the pack (u24/bf16) work.
                with request_trace.span("batch.cache"):
                    inputs = {
                        k: self.input_cache.get_or_put(
                            k, v,
                            pack=(lambda a, _k=k: pack_host({_k: a}, spec)[_k]) if spec else None,
                            pack_tag=spec.get(k, "") if spec else "",
                        )
                        for k, v in arrays.items()
                    }
                with request_trace.span("batch.jitcall"):
                    return fn(
                        k_params, inputs,
                        out_keys=out_keys, topk=topk, n_valid=n_valid,
                        k_apply=k_apply, prune=prune,
                    )
            packed = pack_host(arrays, spec) if spec else arrays
            with request_trace.span("batch.jitcall"):
                return fn(
                    k_params, packed,
                    out_keys=out_keys, topk=topk, n_valid=n_valid,
                    k_apply=k_apply, prune=prune,
                )

    def _shed_expired_locked(self, it: _WorkItem) -> bool:
        """True when `it`'s propagated client deadline already expired —
        the item is failed (DEADLINE_EXCEEDED at the RPC layer) instead of
        dispatched: its waiter stopped listening, so device time spent on
        it would only delay the still-live work behind it. Caller holds
        _cv and has already popped the item."""
        if it.deadline_t is None or time.perf_counter() < it.deadline_t:
            return False
        self.stats.deadline_sheds += 1
        if not it.future.done():
            try:
                it.future.set_exception(
                    RequestDeadlineError(
                        "client deadline expired while queued "
                        f"({time.perf_counter() - it.enqueue_t:.3f}s); "
                        "shed before dispatch"
                    )
                )
            except InvalidStateError:
                # The service-side wait times out at the SAME instant this
                # deadline expires and cancels the future; losing that race
                # must not kill the batcher thread (same guard as
                # _complete's set_result).
                pass
        return True

    def _drop_stale_locked(self, it: _WorkItem) -> bool:
        """Staleness classification for a just-popped queue item — the ONE
        implementation both _take and _coalesce_next use. Cancelled waiter:
        skip the work, and when the item's propagated deadline has actually
        EXPIRED count it as a deadline shed (the RPC wait expires at the
        same instant and withdraws the future first — the common ordering
        over gRPC; a cancellation BEFORE expiry, e.g. the service's 120s
        bound firing under a looser client deadline, is not one).
        Otherwise defer to the expiry shed. True = drop. Caller holds _cv
        and has adjusted _queued_candidates."""
        if it.future.cancelled():
            if it.deadline_t is not None and time.perf_counter() >= it.deadline_t:
                self.stats.deadline_sheds += 1
            return True
        return self._shed_expired_locked(it)

    def _take(self) -> _WorkItem | None:
        """Pop the next live queued item, blocking; None on shutdown after
        the queue drains (every accepted item is still served)."""
        with self._cv:
            while True:
                while self._items:
                    it = self._items.popleft()
                    self._queued_candidates -= it.n
                    if self._drop_stale_locked(it):
                        continue  # cancelled waiter or expired deadline
                    return it
                if self._stopping:
                    return None
                if (util := self.utilization) is not None:
                    # Idle-cause record for the gap waterfall: the device
                    # sat idle because no work arrived (on this rig, the
                    # transport/client-bound share of wall time). Clock
                    # reads only on the idle path.
                    token = util.wait_begin("queue_empty")
                    try:
                        self._cv.wait()
                    finally:
                        util.wait_end(token)
                else:
                    self._cv.wait()

    def _coalesce_next(self, item: _WorkItem, total: int, deadline: float) -> _WorkItem | None:
        """Next same-target item within the (pipeline-extended) window, or
        None. The head item stays put when it doesn't match — deque order is
        preserved (the old SimpleQueue requeue pushed it to the BACK,
        reordering traffic).

        Past `deadline` the wait continues only while the dispatch pipeline
        is saturated (>= pipeline_depth batches in flight and none wedged):
        the next dispatch would queue behind device work regardless, so the
        extra fill time costs no latency. Completion of any in-flight batch
        notifies this wait, ending the free-ride the moment dispatch could
        actually start."""
        free_ride_counted = False
        with self._cv:
            while True:
                while not self._items:
                    now = time.perf_counter()
                    if self._stopping:
                        return None
                    if now < deadline:
                        if (util := self.utilization) is not None:
                            # Coalesce fill: the host deliberately holds
                            # the batch open — device idle charged to
                            # host_pack (clamped out where the pipeline
                            # keeps the device busy underneath).
                            token = util.wait_begin("host_pack")
                            try:
                                self._cv.wait(deadline - now)
                            finally:
                                util.wait_end(token)
                        else:
                            self._cv.wait(deadline - now)
                        continue
                    busy = len(self._inflight) + self._dispatch_pending
                    if busy < self.pipeline_depth or self._wedged_for(now):
                        return None
                    # Free-riding the busy pipeline; a completion notifies.
                    # Bounded wait: the wedge clock advances with wall time
                    # alone, so never sleep unboundedly on the condition.
                    # Counted once per episode, not per poll iteration.
                    if not free_ride_counted:
                        self.stats.fill_waits += 1
                        free_ride_counted = True
                    if (util := self.utilization) is not None:
                        # Pipeline saturated: dispatch blocked behind
                        # in-flight readbacks (idle cause readback_wait).
                        token = util.wait_begin("readback_wait")
                        try:
                            self._cv.wait(0.005)
                        finally:
                            util.wait_end(token)
                    else:
                        self._cv.wait(0.005)
                nxt = self._items[0]
                if nxt.future.cancelled() or (
                    nxt.deadline_t is not None
                    and time.perf_counter() >= nxt.deadline_t
                ):
                    self._items.popleft()
                    self._queued_candidates -= nxt.n
                    self._drop_stale_locked(nxt)
                    continue
                if (
                    nxt.servable is item.servable
                    and not nxt.solo
                    # Bisection halves (recovery plane) only merge with
                    # their OWN half: a half that re-absorbed the other
                    # half's rows would never isolate the poison.
                    and nxt.bisect_key == item.bisect_key
                    and nxt.arrays.keys() == item.arrays.keys()
                    and total + nxt.n <= self.max_batch_candidates
                ):
                    self._items.popleft()
                    self._queued_candidates -= nxt.n
                    return nxt
                return None

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as exc:  # noqa: BLE001 — thread-death watchdog
            # An unhandled exception here would silently kill the batching
            # thread and leave every submitter hanging on the condition
            # variable until its RPC deadline. Fail fast and visibly
            # instead (BatcherThreadDead), and let the recovery plane —
            # when armed — revive the thread and replay the shed work.
            self._note_thread_death("batching", exc)

    def _loop_inner(self) -> None:
        while True:
            item = self._take()
            if item is None:
                return
            group = [item]
            total = item.n
            deadline = item.enqueue_t + self.max_wait_s
            # Coalesce same-servable work until the deadline or size cap.
            # Solo items (streamed sub-batches) dispatch alone: merging
            # them would undo the very split that lets their readbacks
            # complete (and flush) independently.
            while total < self.max_batch_candidates and not item.solo:
                nxt = self._coalesce_next(item, total, deadline)
                if nxt is None:
                    break
                group.append(nxt)
                total += nxt.n
            self._dispatch(group, total)

    def _dispatch(self, group: list[_WorkItem], total: int) -> None:
        """Host-side batch assembly (batcher thread), then the device stage
        — handed to the dispatch thread in pipelined mode so this thread
        returns to collecting+padding batch k+1 while batch k's
        pack/upload/jit-call proceeds (and batch k-1 executes on device)."""
        # Per-request tracing: one phase sink per batch — request_trace's
        # existing call sites (batch.pad here; cache/pack/jitcall/readback
        # on the stage threads) land in it once and are replayed onto
        # EVERY member request's span, so co-batched requests each carry
        # the full batch timeline. None = nobody in this group is traced.
        phases: list | None = (
            [] if tracing.enabled() and any(it.span is not None for it in group)
            else None
        )
        # Donation-safe buffer ring: padded-batch buffers acquired here are
        # released only after the batch fully completes (the completer's
        # finally) or on a pre-device failure path — never while the async
        # H2D upload could still be reading them.
        ring = self.buffer_ring
        ring_bufs: list = []

        def pad_buffer(shape: tuple, dtype) -> np.ndarray:
            if ring is None:
                return np.empty(shape, dtype)
            buf = ring.acquire(shape, dtype)
            ring_bufs.append(buf)
            return buf

        row_ctx: _RowContext | None = None
        try:
            bucket = bucket_for(total, self.buckets)
            first = group[0]
            # Union of the group's wanted outputs; None on any item = all.
            # Computed up front: output selection is traced into the jitted
            # entry, and the top-k gate needs it.
            wanted: set[str] | None = set()
            for it in group:
                if it.output_keys is None:
                    wanted = None
                    break
                wanted.update(it.output_keys)
            wanted_key = tuple(sorted(wanted)) if wanted is not None else None
            # Top-k output compaction: single-request retrieval-style
            # batches whose caller asked for exactly the score vector. A
            # coalesced group cannot ride it (top-k over concatenated
            # requests would mix candidates across requests).
            topk, n_valid, prune = 0, None, False
            if (
                self.output_top_k
                and self._run_fn is None
                and len(group) == 1
                and not first.warmup
                and 0 < self.output_top_k < first.n
                and wanted_key == (first.servable.model.score_output,)
                and not first.servable.model.needs_x64
            ):
                topk, n_valid = self.output_top_k, first.n
            # Cascade stage-1 prune (ISSUE 19): a prune submit rides the
            # same on-device selection machinery as top-k compaction (and
            # reuses its k/n_valid plumbing) but returns the survivor
            # pairs PLUS the wire-dtype stage-1 vector. Prune items are
            # solo, so the group is single-request by construction; when
            # the variant cannot arm (custom run_fn, x64 model, k >= n)
            # the batch runs as a normal full-vector execution and the
            # orchestrator selects survivors on host — counted so the
            # fallback rate is visible.
            if first.prune_k and not first.warmup:
                if (
                    self._run_fn is None
                    and len(group) == 1
                    and 0 < first.prune_k < first.n
                    and wanted_key == (first.servable.model.score_output,)
                    and not first.servable.model.needs_x64
                ):
                    topk, n_valid, prune = first.prune_k, first.n, True
                else:
                    self.stats.prune_fallback_batches += 1
            # Intra-batch duplicate collapse (cache/dedup.py): exact-bytes
            # duplicate rows across the combined batch execute ONCE; the
            # completer scatters the unique rows' scores back into every
            # requester's original order. Skipped for top-k batches (the
            # returned indices address original rows) and warmup groups
            # (all-zero warmup rows would collapse to one and compile the
            # wrong bucket).
            scatter = None
            dedup_cats = None
            # Row-granular score cache (ISSUE 14): digest + look up every
            # row after collect, pack/dispatch only the cold ones. The
            # plan subsumes the dedup block below (its unique-collapse
            # runs inside _plan_rows when [cache] dedup is armed, and
            # intra-batch duplicates additionally coalesce onto one row
            # flight), so exactly one of the two paths runs per batch.
            # Top-k batches are excluded (the returned indices address
            # original rows) and warmup groups (all-zero rows would
            # collapse and poison the cache with compile traffic).
            rc = self.row_cache
            if (
                rc is not None
                and not topk
                and not any(it.warmup for it in group)
            ):
                with (tracing.collect_phases(phases) if phases is not None
                      else _NULL_CTX), request_trace.span("cache.row_lookup"):
                    row_ctx = self._plan_rows(rc, group, total, wanted_key)
                self.stats.row_batches += 1
                self.stats.rows_requested += total
                self.stats.rows_executed += row_ctx.n_cold
                if row_ctx.n_cold == 0:
                    # Every row answered from cache (or a foreign
                    # in-flight fill): no device work at all. Delivery
                    # rides a completer so the batching thread never
                    # blocks on another batch's fill.
                    self.stats.row_full_hit_batches += 1
                    if phases is not None:
                        _replay_group_phases(group, phases)
                    self._completers.submit(
                        self._complete_rows_only, group, row_ctx
                    ).add_done_callback(
                        lambda f, g=group: self._guard_worker_future(
                            f, g, "completer"
                        )
                    )
                    return
                if row_ctx.passthrough:
                    # Every row cold and distinct: execution covers the
                    # original batch in original order — the normal
                    # pad/fused paths serve it from the concat the plan
                    # already built; only the fill rides along.
                    dedup_cats = row_ctx.exec_arrays
                else:
                    bucket = bucket_for(row_ctx.n_cold, self.buckets)
                    dedup_cats = row_ctx.exec_arrays
            elif (
                self.dedup
                and not topk
                and total > 1
                and not any(it.warmup for it in group)
            ):
                with (tracing.collect_phases(phases) if phases is not None
                      else _NULL_CTX), request_trace.span("batch.dedup"):
                    uniq, scatter, dedup_cats = collapse_rows(
                        {k: [it.arrays[k] for it in group] for k in first.arrays}
                    )
                if scatter is not None:
                    n_unique = next(iter(uniq.values())).shape[0]
                    bucket = bucket_for(n_unique, self.buckets)
                    self.stats.dedup_batches += 1
                    self.stats.dedup_rows_collapsed += total - n_unique
            # A collapsed batch skips the fused assembler: its native pack
            # reads the ORIGINAL per-request parts, which would re-inflate
            # the rows dedup just removed.
            fused = None if scatter is not None else self._fused_ctx(group, bucket)
            if fused is not None and dedup_cats is not None:
                # All-unique screen with the fused path winning: hand the
                # packer the screen's concatenated arrays as single parts
                # (its output is row-sequential, so one pre-concatenated
                # part packs bit-identically to the original part list) —
                # the screen's concat is reused here too, never discarded.
                fused["ids_parts"] = [dedup_cats["feat_ids"]]
                fused["wts_parts"] = [dedup_cats["feat_wts"]]
            batched = None
            if fused is None and (scatter is not None or dedup_cats is not None):
                # Pad from the dedup screen's arrays: the unique rows when
                # duplicates collapsed, else the concatenated batch
                # collapse_rows built anyway (all-unique outcome) — never
                # a SECOND concat of the same parts.
                src = uniq if scatter is not None else dedup_cats
                batched = {}
                with (tracing.collect_phases(phases) if phases is not None
                      else _NULL_CTX), request_trace.span("batch.pad"):
                    for k, arr in src.items():
                        if arr.shape[0] == bucket:
                            # Owned either way: a multi-part concat, a
                            # first-occurrence gather, or a single item's
                            # prepare_inputs-owned array (same passthrough
                            # contract as the generic pad path below).
                            batched[k] = arr
                            continue
                        out = pad_buffer((bucket,) + arr.shape[1:], arr.dtype)
                        out[: arr.shape[0]] = arr
                        out[arr.shape[0]:] = 0  # padding rows
                        batched[k] = out
            elif fused is None:
                keys = list(first.arrays.keys())
                batched = {}
                with (tracing.collect_phases(phases) if phases is not None
                      else _NULL_CTX), request_trace.span("batch.pad"):
                    for k in keys:
                        parts = [it.arrays[k] for it in group]
                        if len(parts) == 1 and parts[0].shape[0] == bucket:
                            # Safe to pass through uncopied: prepare_inputs
                            # guarantees item arrays never alias caller buffers.
                            batched[k] = parts[0]
                            continue
                        # Single allocation + one copy per part (no concat temporaries).
                        # Mixed dtypes (an int64 wire request coalesced with a
                        # pre-folded int32 direct submit) widen, never wrap.
                        dt = parts[0].dtype
                        if any(p.dtype != dt for p in parts):
                            dt = np.result_type(*(p.dtype for p in parts))
                        out = pad_buffer((bucket,) + parts[0].shape[1:], dt)
                        off = 0
                        for p in parts:
                            out[off : off + p.shape[0]] = p
                            off += p.shape[0]
                        out[off:] = 0  # padding rows
                        batched[k] = out
        except Exception as exc:  # assembly failed: fail the group, keep serving
            if ring is not None and ring_bufs:
                ring.release(ring_bufs)
            if row_ctx is not None:
                # Close the plan's row flights: foreign batches waiting on
                # this batch's cold rows fail now instead of hanging.
                row_ctx.abort(exc)
            for it in group:
                if not it.future.done():
                    it.future.set_exception(exc)
            return
        if self._dispatcher is None:
            self._run_stage(
                None, group, total, bucket, wanted, wanted_key,
                topk, n_valid, fused, batched, phases, scatter, ring_bufs,
                row_ctx, prune,
            )
            return
        with self._cv:
            self._staged_seq += 1
            sid = self._staged_seq
            self._staged_groups[sid] = (group, total)
            self._staged_candidates += total
            self._dispatch_pending += 1
        self._dispatcher.submit(
            self._run_stage, sid, group, total, bucket, wanted, wanted_key,
            topk, n_valid, fused, batched, phases, scatter, ring_bufs,
            row_ctx, prune,
        ).add_done_callback(
            # Thread-death guard: _run_stage catches Exception broadly,
            # so only a BaseException (or a bug in its own finally) can
            # escape — which would leave this group's waiters hanging and
            # the stage slot poisoned. Fail them fast instead.
            lambda f, g=group: self._guard_worker_future(f, g, "dispatch")
        )
        # Backpressure: up to pipeline_depth-1 groups may queue behind the
        # running stage — enough to keep the pipeline full (assembly of
        # k+1 overlaps the stage of k; deeper depths stage further ahead),
        # bounded so a slow device never lets the batcher thread run
        # arbitrarily far ahead of admission control. Depth 1 serializes
        # assembly against the stage. Bounded waits: the wedge clock
        # advances on wall time.
        with self._cv:
            while (
                self._dispatch_pending >= self.pipeline_depth
                and not self._stopping
            ):
                self._cv.wait(0.005)

    def _plan_rows(
        self, rc, group: list[_WorkItem], total: int,
        wanted_key: tuple | None,
    ) -> _RowContext:
        """Row-granular cache consultation for one collected batch: build
        the concatenated batch, digest each row (dedup-unique rows only
        when [cache] dedup is armed — the collapse_rows machinery
        generalized), and classify every slot hit / foreign-flight waiter
        / cold. The returned context carries the gathered COLD rows as
        the batch to execute and the inverse map the completer scatters
        through. Runs on the batcher thread (the dedup precedent); the
        per-row blake2b digests are the plane's host cost, paid only
        while it is armed."""
        from ..cache.row_cache import digest_rows, row_structure_header

        first = group[0]
        # np.concatenate widens mixed dtypes exactly like the pad loop
        # (an int64 wire request coalesced with a pre-folded int32 direct
        # submit), so row identity is over the bytes the device would see.
        cats = {
            k: (np.concatenate([it.arrays[k] for it in group])
                if len(group) > 1 else first.arrays[k])
            for k in first.arrays
        }
        blob = canonical_rows(cats)
        header = row_structure_header(cats)
        digests_all = digest_rows(blob, header)
        uniq_rows = None
        inverse = None
        if self.dedup and total > 1:
            # Duplicate collapse by DIGEST, not by the raw 300+-byte row
            # blob: the cache keys rows by this digest anyway (so
            # digest-equal IS the plane's identity — collapsing by it
            # adds no failure mode the keying doesn't already have), and
            # np.unique over 16-byte rows is ~24x cheaper than over the
            # full canonical bytes (1.5 ms vs 36 ms at a 1.5k x 43
            # batch) — the row plane's collapse is CHEAPER than
            # collapse_rows, not dearer.
            darr = np.frombuffer(b"".join(digests_all), np.uint8)
            _, first_idx, inv = np.unique(
                darr.reshape(total, 16), axis=0,
                return_index=True, return_inverse=True,
            )
            if first_idx.shape[0] < total:
                uniq_rows = first_idx
                inverse = inv.reshape(-1).astype(np.int64)
                self.stats.dedup_batches += 1
                self.stats.dedup_rows_collapsed += total - first_idx.shape[0]
        if uniq_rows is None:
            uniq_rows = np.arange(total, dtype=np.int64)
            inverse = uniq_rows
        digests = (
            digests_all if uniq_rows.shape[0] == total
            else [digests_all[i] for i in uniq_rows]
        )
        ov = self.overload
        # Brownout stale-serve extends to row entries: while pressure is
        # past NOMINAL, an expired row still answers (marked degraded at
        # delivery, never re-filled) — the whole-request stale-serve
        # contract at row granularity.
        stale_s = (
            ov.stale_window_s
            if ov is not None and ov.stale_serve_active()
            else 0.0
        )
        servable = first.servable
        plan = rc.begin_rows(
            servable.name, servable.version, wanted_key, digests,
            stale_s=stale_s,
        )
        try:
            ctx = _RowContext()
            ctx.cache = rc
            ctx.plan = plan
            ctx.overload = ov
            ctx.n_slots = len(digests)
            ctx.inverse = inverse
            ctx.lead_slots = np.asarray(plan.lead, dtype=np.int64)
            ctx.n_cold = len(plan.lead)
            ctx.passthrough = ctx.n_cold == ctx.n_slots == total
            # All slots executed fresh by THIS batch (no cached rows, no
            # foreign flights): delivery can ride the normal completer
            # tail — including the quality feed — via a plain inverse
            # scatter, exactly like the dedup path it subsumes.
            ctx.all_fresh = not plan.hits and not plan.waiters
            if ctx.passthrough:
                # Execution == the original batch: pad/fuse straight from
                # the concat this plan already built (never a second
                # concat).
                ctx.exec_arrays = cats
            elif ctx.n_cold:
                rows = uniq_rows[ctx.lead_slots]
                ctx.exec_arrays = {
                    k: np.ascontiguousarray(v[rows]) for k, v in cats.items()
                }
            else:
                ctx.exec_arrays = None
            rc.note_rows(servable.name, total, ctx.n_cold)
        except BaseException as exc:
            # The flights begin_rows registered must not outlive a failed
            # plan — a foreign batch joining them later would hang on a
            # fill that can never land (begin_rows' own atomicity guard
            # covers only its internal loop).
            rc.abort_rows(plan, exc)
            raise
        return ctx

    def _complete_rows_only(self, group: list[_WorkItem], row_ctx) -> None:
        """Completer task for a batch with ZERO cold rows: assemble every
        request's outputs from cached hits and foreign in-flight fills —
        the device, the bucket ladder, and the dispatch pipeline are
        never touched."""
        self._finish_row_batch(group, row_ctx, None)

    def _finish_row_batch(
        self, group: list[_WorkItem], row_ctx, host: dict | None
    ) -> None:
        """Deliver a row-cache batch once every foreign fill it joined has
        resolved. Never blocks a completer thread: when foreign waiters
        are still in flight, delivery re-enters from the LAST waiter's
        done-callback (on the resolving leader's thread) — deadlock-free
        by construction, whatever the completer pool's size."""
        pending = [f for f in row_ctx.plan.waiters.values() if not f.done()]
        if not pending:
            self._deliver_row_batch(group, row_ctx, host)
            return
        lock = threading.Lock()
        state = {"left": len(pending)}

        def _on_done(_f):
            with lock:
                state["left"] -= 1
                if state["left"]:
                    return
            try:
                self._deliver_row_batch(group, row_ctx, host)
            except Exception as exc:  # noqa: BLE001 — waiters must resolve
                for it in group:
                    if not it.future.done():
                        try:
                            it.future.set_exception(exc)
                        except InvalidStateError:
                            pass

        for f in pending:
            f.add_done_callback(_on_done)

    def _deliver_row_batch(
        self, group: list[_WorkItem], row_ctx, host: dict | None
    ) -> None:
        """Scatter (device + cached + foreign-filled) rows back into every
        request's original slice and resolve the futures. A request any
        of whose rows rode a FAILED foreign fill gets that error (its
        batchmates still deliver); a request served any stale (brownout)
        row is marked degraded via the future side-channel the service
        reads after the wait. Cache-assembled batches are deliberately
        NOT fed to the quality plane: like whole-request cache hits,
        their non-cold rows are served — not freshly predicted — scores
        (the passthrough case rides the normal completer tail and is
        sketched there)."""
        try:
            full, failed_rows, row_errors = row_ctx.assemble(host)
        except Exception as exc:  # noqa: BLE001 — every waiter must resolve
            for it in group:
                if not it.future.done():
                    try:
                        it.future.set_exception(exc)
                    except InvalidStateError:
                        pass
            return
        stale = row_ctx.plan.stale_slots
        stale_rows = (
            np.isin(row_ctx.inverse, np.fromiter(stale, np.int64))
            if stale else None
        )
        ov = row_ctx.overload
        off = 0
        for it in group:
            sl = slice(off, off + it.n)
            off += it.n
            if failed_rows is not None and failed_rows[sl].any():
                bad = int(row_ctx.inverse[sl][failed_rows[sl]][0])
                exc = row_errors.get(bad) or next(iter(row_errors.values()))
                if not it.future.done():
                    try:
                        it.future.set_exception(exc)
                    except InvalidStateError:
                        pass
                continue
            if stale_rows is not None and stale_rows[sl].any():
                # Degraded marker: the service thread reads this after
                # the future resolves (it cannot be set from here — the
                # contextvar lives in the RPC's context) and forwards it
                # as the x-dts-degraded trailing metadata / header.
                it.future.dts_degraded = "stale"
                if ov is not None:
                    ov.note_brownout_serve()
                if it.span is not None:
                    it.span.attrs["brownout_stale_rows"] = True
                    it.span.annotate(
                        "overload.stale_serve",
                        rows=int(stale_rows[sl].sum()),
                    )
            sliced = {k: v[sl] for k, v in full.items()}
            try:
                if not it.future.cancelled():
                    it.future.set_result(sliced)
            except InvalidStateError:
                pass

    def _run_stage(
        self,
        sid: int | None,
        group: list[_WorkItem],
        total: int,
        bucket: int,
        wanted: set | None,
        wanted_key: tuple | None,
        topk: int,
        n_valid: int | None,
        fused: dict | None,
        batched: dict | None,
        phases: list | None = None,
        scatter: "np.ndarray | None" = None,
        ring_bufs: list | None = None,
        row_ctx: "_RowContext | None" = None,
        prune: bool = False,
    ) -> None:
        """Device stage for one assembled batch: execute, issue the async
        D2H readback, register in flight, hand off to a completer. Runs on
        the dispatch thread (pipelined mode) or inline on the batcher
        thread (sid None from the fallback path). `phases` is the batch's
        tracing sink (started in _dispatch with the pad phase); the device-
        stage phases and fault annotations land in it here and are
        replayed onto every member request's span."""
        pending_closed = sid is None
        util = None  # assigned once the batch passes the early-out checks
        util_handed_off = False
        # Elastic run_fn completion protocol (parallel/elastic.py): the
        # dispatch below mints a per-batch issue token naming the split it
        # routed to; the completer's finally closes it (note_complete) —
        # the per-split in-flight accounting that is the hitless-switch
        # drain barrier. Captured here so a run_fn detached mid-flight
        # still gets its token back.
        run_fn_cap = self._run_fn
        run_token = None
        run_handed = False

        def release_bufs():
            # Pre-completion exit (shed, all-cancelled, device-stage
            # failure): the buffers were never handed to a completer, and
            # no async upload is in flight past this frame, so they are
            # safe to recycle here.
            if self.buffer_ring is not None and ring_bufs:
                self.buffer_ring.release(ring_bufs)

        def sink_ctx():
            # Fresh context per use: collect_phases is a generator context
            # manager (single-shot), and this stage enters the sink twice
            # (device stage, readback issue).
            return (
                tracing.collect_phases(phases)
                if phases is not None else _NULL_CTX
            )

        try:
            if sid is not None:
                with self._cv:
                    if self._staged_groups.pop(sid, None) is None:
                        release_bufs()
                        if row_ctx is not None:
                            # Shed while staged: foreign batches waiting
                            # on this batch's cold rows must fail now.
                            row_ctx.abort(DeviceWedgedError(
                                "batch shed while staged for dispatch"
                            ))
                        return  # shed by the circuit breaker while queued
                    self._staged_candidates -= total
            if all(it.future.cancelled() for it in group):
                release_bufs()
                if row_ctx is not None:
                    row_ctx.abort(CoalescedLeaderCancelled(
                        "row fill leader batch was cancelled before dispatch"
                    ))
                return  # every waiter gave up; skip the device work
            all_warm = all(it.warmup for it in group)
            window = self.inflight_window
            if window and not all_warm:
                # The k-deep in-flight window: keep issuing while fewer
                # than k batches are executing-or-awaiting-readback; at k,
                # wait for a completion (notified from _complete's
                # finally). Bounded waits, and a wedged readback breaks
                # the gate — the jit call would queue behind the wedged
                # device anyway, and the breaker owns that failure mode.
                waited_for_window = False
                with self._cv:
                    while (
                        len(self._inflight) >= window
                        and not self._stopping
                        and not self._wedged_for(time.perf_counter())
                    ):
                        if not waited_for_window:
                            self.stats.inflight_window_waits += 1
                            waited_for_window = True
                        self._cv.wait(0.005)
            with self._cv:
                # An all-warmup group is exempt from the wedge clock:
                # hot-load warmup (warmup_via_queue during a version
                # rollout) legitimately compiles for minutes here, and
                # tripping the breaker then would shed live traffic during
                # every rollout. A live request coalesced into the group
                # re-arms the clock.
                self._dispatching_since = (
                    None if all_warm else time.perf_counter()
                )
                if self.recovery is not None:
                    # The group now entering the device stage — what a
                    # wedge-triggered quarantine capture must replay.
                    self._dispatching_group = None if all_warm else group
            servable = group[0].servable
            stage_t0 = time.perf_counter()
            # Utilization ledger: captured here (detachable mid-flight,
            # the overload/cache precedent) and handed to the completer so
            # the depth gauge's inc/dec stay paired even if the plane is
            # swapped while this batch is in flight. Warmup batches are
            # compile time, not device occupancy.
            util = None if all_warm else self.utilization
            if util is not None:
                util.depth_inc()
            ov = self.overload  # capture: detachable mid-flight (bench A/B)
            if ov is not None:
                # Feed the controller the group's measured queue waits —
                # the controlled variable of the adaptive admission loop.
                # Warmup items are exempt (their waits include compiles).
                waits = [
                    stage_t0 - it.enqueue_t for it in group if not it.warmup
                ]
                if waits:
                    ov.note_queue_waits(waits)
            if phases is not None:
                # Queue wait is per-item (each enqueued at its own time);
                # attached directly, not through the shared batch sink.
                now = time.perf_counter()
                for it in group:
                    if it.span is not None:
                        it.span.add_interval("batch.queue_wait", it.enqueue_t, now)
            with sink_ctx():
                # Named fault site (faults.py): delay/error/wedge the device
                # stage of this batch — the stuck-device scenario the circuit
                # breaker and deadline tests drive deterministically. Inside
                # the sink so an injected fault annotates the member spans.
                faults.fire("batcher.dispatch")
                if faults.active() and faults.get().has_site("device_lost"):
                    # Recovery-plane chaos site: fired once per member
                    # request with that request's content digest as the
                    # key — a keyless rule kills any batch (device died),
                    # a keyed rule deterministically kills exactly the
                    # batches carrying one request's bytes (the poison
                    # the bisection isolates). The has_site gate keeps
                    # ordinary chaos runs from paying the digests.
                    for it in group:
                        faults.fire(
                            "device_lost", key=poison_fault_key(it.arrays)
                        )
                with request_trace.span("batch.dispatch"):
                    if fused is not None:
                        outputs = self._execute_fused(
                            fused, bucket, wanted_key, topk, n_valid,
                            prune=prune,
                        )
                        self.stats.fused_batches += 1
                    else:
                        outputs = self._execute(  # async dispatch
                            servable, batched,
                            out_keys=wanted_key, topk=topk, n_valid=n_valid,
                            prune=prune,
                        )
            if run_fn_cap is not None and getattr(run_fn_cap, "elastic", False):
                # Same thread, synchronous: the token names the split the
                # dispatch above routed to. It travels to the completer
                # and closes there (or in this frame's finally on a
                # pre-handoff failure).
                run_token = run_fn_cap.take_issue_token()
            if topk:
                if prune:
                    self.stats.prune_batches += 1
                else:
                    self.stats.topk_batches += 1
                # Top-k / prune outputs ARE the fetch (the score vector is
                # reconstructed host-side from the pairs).
                fetch = dict(outputs)
            else:
                fetch = {
                    k: v for k, v in outputs.items()
                    # int8-wire scale/min sidecars always ride the fetch:
                    # a filtered request's quantized score is undecodable
                    # without them (restore_outputs_host strips them).
                    if wanted is None or k in wanted or is_wire_sidecar(k)
                }
            shadow_fetch = None
            integ = self.integrity
            if (
                integ is not None
                and run_fn_cap is None
                and not all_warm
                and integ.want_shadow()
            ):
                # Shadow verification (ISSUE 20): re-execute the SAME
                # jitted entry over the same inputs — donation-safe
                # because the shadow arrays are host buffers device_put
                # fresh per _execute call — and hand both device results
                # to the completer for a host-side bit-identity compare.
                # Any divergence is hardware miscomputation (same
                # program, same input, one device): OutputCorruptError
                # there captures the group for replay via the recovery
                # cycle. Custom run_fn paths are ineligible (their
                # entries may legitimately not be bit-stable); all-warmup
                # groups carry no scores worth verifying.
                if batched is not None:
                    shadow_in = batched
                else:
                    # Fused-assembler batch: rebuild the generic padded
                    # equivalent from the same host parts the native
                    # packer consumed. The generic entry shares the
                    # fused path's compiled executable over a
                    # bit-identical combined buffer (pinned by
                    # tests/test_batcher.py), so the compare stays
                    # apples to apples — and cross-checks the native
                    # assembler against the reference pad+pack besides.
                    # Plain np.empty, not the buffer ring: this buffer
                    # dies with the dispatch frame.
                    shadow_in = {}
                    for k, parts in (
                        ("feat_ids", fused["ids_parts"]),
                        ("feat_wts", fused["wts_parts"]),
                    ):
                        dt = parts[0].dtype
                        if any(p.dtype != dt for p in parts):
                            dt = np.result_type(*(p.dtype for p in parts))
                        buf = np.empty(
                            (bucket,) + parts[0].shape[1:], dt
                        )
                        off = 0
                        for p in parts:
                            buf[off : off + p.shape[0]] = p
                            off += p.shape[0]
                        buf[off:] = 0  # padding rows
                        shadow_in[k] = buf
                with sink_ctx():
                    with request_trace.span("batch.shadow_dispatch"):
                        shadow_outputs = self._execute(
                            servable, shadow_in,
                            out_keys=wanted_key, topk=topk, n_valid=n_valid,
                            prune=prune,
                        )
                shadow_fetch = {k: shadow_outputs[k] for k in fetch}
            # What a full-fp32 all-outputs readback of this batch would
            # have moved: the baseline the compaction win is charged
            # against. Traced row bytes when the default jit entry served
            # the batch; the f32-equivalent of the fetch for custom
            # run_fns (their dropped outputs are unknowable here).
            rb = self._out_row_bytes.get(servable)
            if rb is not None and rb[0]:
                full_bytes = rb[0] * bucket
            else:
                # Custom run_fn outputs may be arbitrary array-likes; only
                # count what exposes a shape.
                full_bytes = sum(
                    int(np.prod(shape)) * 4
                    for v in fetch.values()
                    if (shape := getattr(v, "shape", None)) is not None
                )
            issue_t0 = time.perf_counter()
            if self.async_readback:
                # Start the device->host readback now; the completer thread
                # then finds the bytes already (or sooner) on host.
                for v in fetch.values():
                    if hasattr(v, "copy_to_host_async"):
                        v.copy_to_host_async()
                if shadow_fetch is not None:
                    for v in shadow_fetch.values():
                        if hasattr(v, "copy_to_host_async"):
                            v.copy_to_host_async()
                with sink_ctx():
                    request_trace.add(
                        "readback.issue", time.perf_counter() - issue_t0
                    )

            self.stats.batches += 1
            self.stats.requests += len(group)
            self.stats.candidates += total
            self.stats.padded_candidates += bucket
            self.stats.bytes_download_full_f32 += int(full_bytes)

            meta = None
            if topk:
                meta = {
                    ("prune_n" if prune else "topk_n"): n_valid,
                    "score_key": servable.model.score_output,
                }
            # Readback + distribution off-thread: this thread moves on to
            # the next batch immediately, pipelining device work. The batch
            # is registered in-flight first so a readback that never
            # returns is visible to the circuit breaker.
            with self._cv:
                self._inflight_seq += 1
                batch_id = self._inflight_seq
                if not all(it.warmup for it in group):
                    self._inflight[batch_id] = time.perf_counter()
                    if self.recovery is not None:
                        # Same register site as the wedge clock: a
                        # quarantine capture replays exactly the groups
                        # the stuck readbacks strand.
                        self._inflight_groups[batch_id] = group
                    # Per-bucket in-flight accounting + high-water mark
                    # (pipeline_stats / dts_tpu_pipeline_*): same locked
                    # register site as the wedge clock, popped together
                    # in _complete's finally.
                    self._inflight_buckets[bucket] = (
                        self._inflight_buckets.get(bucket, 0) + 1
                    )
                    self.stats.inflight_peak = max(
                        self.stats.inflight_peak, len(self._inflight)
                    )
                # Wedge accounting moves from "dispatching" to "in flight"
                # atomically. Clearing only in the finally below would leave
                # a window where the completer has already resolved this
                # batch's futures while _dispatching_since still shows the
                # dispatch start — a submit racing that window would read a
                # long-finished dispatch as a wedged device.
                self._dispatching_since = None
                self._dispatching_group = None
                if not pending_closed:
                    # Clamped at zero: a quarantine capture resets the
                    # pending count while abandoned stage calls may still
                    # be queued behind a wedged worker — their eventual
                    # decrements must not drive it negative.
                    self._dispatch_pending = max(self._dispatch_pending - 1, 0)
                    pending_closed = True
                self._cv.notify_all()
            if phases is not None:
                _replay_group_phases(group, phases)
                phases = None  # a later submit() failure must not re-replay
            self._completers.submit(
                self._complete, batch_id, group, fetch, issue_t0, meta, scatter,
                stage_t0, util=util, bucket=bucket, ring_bufs=ring_bufs,
                row_ctx=row_ctx, run_token=run_token,
                run_fn=run_fn_cap if run_token is not None else None,
                shadow=shadow_fetch,
            ).add_done_callback(
                lambda f, g=group: self._guard_worker_future(f, g, "completer")
            )
            util_handed_off = True
            run_handed = True
        except Exception as exc:  # propagate to every waiter, keep serving
            # Ring buffers are deliberately NOT recycled on a device-stage
            # failure: an async H2D transfer may still be reading them, so
            # they fall to GC instead (the ring just allocates fresh ones).
            if phases is not None:
                # The spans must show the phases (and any injected-fault
                # annotation) that led to the failure BEFORE the waiters
                # unblock and finish their root spans.
                _replay_group_phases(group, phases)
            if row_ctx is not None:
                # Close the row flights whatever happens next: even when
                # the recovery plane replays this group (re-planning its
                # rows fresh), foreign batches riding the OLD flights
                # must not hang on a fill that will never land.
                row_ctx.abort(exc)
            rec = self.recovery  # capture: detachable mid-flight
            if rec is not None and rec.take_group(group, exc):
                # Device-fatal failure with the recovery plane armed: the
                # controller owns these items now (quarantine -> reinit ->
                # replay); their futures resolve from the replay path —
                # or with a distinct poisoned/budget-exhausted status —
                # never from this frame.
                pass
            else:
                for it in group:
                    if not it.future.done():
                        it.future.set_exception(exc)
        finally:
            if util is not None and not util_handed_off:
                # A device-stage failure never reaches _complete: close
                # the gauge here so in_flight cannot drift upward.
                util.depth_dec()
            if run_token is not None and not run_handed:
                # A minted-but-never-handed-off token (post-dispatch
                # failure before the completer submit) must close here,
                # or the elastic drain barrier holds open forever.
                try:
                    run_fn_cap.note_complete(run_token)
                except Exception:  # noqa: BLE001 — accounting, never fatal
                    pass
            with self._cv:
                self._dispatching_since = None
                self._dispatching_group = None
                if not pending_closed:
                    self._dispatch_pending = max(self._dispatch_pending - 1, 0)
                self._cv.notify_all()

    def _complete(
        self, batch_id: int, group: list[_WorkItem], outputs,
        issue_t0: float | None = None, meta: dict | None = None,
        scatter: "np.ndarray | None" = None,
        stage_t0: float | None = None,
        util=None, bucket: int = 0,
        ring_bufs: list | None = None,
        row_ctx: "_RowContext | None" = None,
        run_token=None, run_fn=None,
        shadow: dict | None = None,
    ) -> None:
        phases: list | None = (
            [] if tracing.enabled() and any(it.span is not None for it in group)
            else None
        )
        trace_ctx = (
            tracing.collect_phases(phases) if phases is not None else _NULL_CTX
        )
        taken_by_recovery = False
        try:
            with trace_ctx:
                # Named fault sites (faults.py): a readback that stalls or
                # dies — inside the sink so chaos annotates member spans —
                # and the recovery plane's executor_abort (the executable
                # aborted after dispatch; classified device-fatal).
                faults.fire("readback")
                faults.fire("executor_abort")
                # The fetch: with async_readback the copy is already in
                # flight (issued at dispatch), so this measures the residual
                # WAIT, not a full synchronous transfer — the split the
                # phase names carry.
                wait_t0 = time.perf_counter()
                host = {k: np.asarray(v) for k, v in outputs.items()}
                done_t = time.perf_counter()
                waited = done_t - wait_t0
                request_trace.add(
                    "readback.wait" if self.async_readback else "batch.readback",
                    waited,
                )
            integ = self.integrity  # capture: detachable mid-flight
            if (
                integ is not None
                and faults.active()
                and (
                    faults.get().has_site("readback_bitflip")
                    or faults.get().has_site("score_nan")
                )
            ):
                # Chaos injection BEFORE the shadow compare and screen:
                # the corrupted bytes must be exactly what those layers
                # would have received from a sick readback path.
                host = _inject_readback_corruption(host, group)
            if integ is not None and shadow is not None:
                # Shadow verification: bit-identity compare of the two
                # executions' raw host bytes, BEFORE widen/scatter (any
                # post-processing is deterministic host numpy — comparing
                # the rawest form localizes blame to the device/readback
                # path). Raises OutputCorruptError on divergence: the
                # except below hands the group to recovery for replay.
                keys = sorted(host)
                integ.shadow_compare(
                    [host[k] for k in keys],
                    [np.asarray(shadow[k]) for k in keys],
                )
            downloaded = sum(v.nbytes for v in host.values())
            total_n = sum(it.n for it in group)
            ov = self.overload  # capture: detachable mid-flight (bench A/B)
            if (
                ov is not None
                and stage_t0 is not None
                and not any(it.warmup for it in group)
            ):
                # Per-candidate service time (dispatch start -> readback
                # done): the EWMA estimate that prices backlogs for the
                # doomed-work refusal and the retry-after hint. Warmup
                # batches are excluded (compile time is not service time).
                ov.note_batch(total_n, done_t - stage_t0)
            if util is not None and stage_t0 is not None:
                # THE interval append the utilization plane is built on:
                # one (stage-start, readback-issued, readback-done) triple
                # per batch closes the preceding idle gap, extends the
                # busy union, and feeds the windowed gap waterfall.
                util.note_batch(
                    stage_t0, issue_t0 if issue_t0 is not None else done_t,
                    done_t, bucket=bucket, candidates=total_n,
                    d2h_wait_s=waited,
                )
            window = max(done_t - issue_t0 if issue_t0 is not None else waited, waited)
            with self._cv:  # counters race across completer threads otherwise
                self.stats.bytes_downloaded += downloaded
                self.stats.readback_window_s += window
                self.stats.readback_blocked_s += (
                    waited if self.async_readback else window
                )
            if meta is not None and "prune_n" in meta:
                # Cascade stage-1 prune: widen the wire-dtype arrays to
                # f32 and hand all three through — the orchestrator does
                # the survivor gather/scatter. The per-item slice below
                # passes the k-length pairs through untouched (k < n) and
                # trims the bucket-length stage-1 vector to the request's
                # own rows (single solo request by construction).
                host = {
                    "survivor_scores":
                        host["survivor_scores"].astype(np.float32),
                    "survivor_indices": host["survivor_indices"],
                    "stage1_scores": host["stage1_scores"].astype(np.float32),
                }
            elif meta is not None:
                # Top-k reconstruction: scatter the k (score, index) pairs
                # back into a full-length f32 vector (single-request group
                # by construction).
                host = topk_restore_host(
                    host["topk_scores"], host["topk_indices"],
                    int(meta["topk_n"]), meta["score_key"],
                )
            elif self._wire_dt is not None:
                # Wire-dtype outputs widen back to float32 HERE, so every
                # downstream consumer (codec encode, Classify/Regress,
                # response assembly) transparently sees the signature dtype.
                # Gated on the knob: with the float32 wire, a model whose
                # outputs are GENUINELY half-precision (imported graphs
                # declaring DT_HALF/DT_BFLOAT16) must pass through
                # untouched, exactly as before this pipeline existed.
                host = restore_outputs_host(host)
            if scatter is not None:
                # Dedup scatter: the executable saw only the batch's unique
                # rows; fan their scores back out to every original row
                # position, so the per-request slices below are exactly
                # what an uncollapsed execution would have produced.
                host = {k: v[scatter] for k, v in host.items()}
            if row_ctx is not None:
                # Row-cache fill: close the plan's lead flights from the
                # executed rows (post-widen, post-sidecar-consume — the
                # exact bytes delivery slices) and wake every foreign
                # batch waiting on them.
                with (
                    tracing.collect_phases(phases) if phases is not None
                    else _NULL_CTX
                ), request_trace.span("cache.row_fill"):
                    row_ctx.fill_from_host(host)
            if phases is not None:
                # Attach the readback phases before the waiters unblock —
                # a root span must already hold its full tree when the RPC
                # handler finishes (and records) it.
                _replay_group_phases(group, phases)
                phases = None  # a set_result failure must not re-replay
            if row_ctx is not None and not row_ctx.passthrough:
                if row_ctx.all_fresh:
                    # Every delivered score came from THIS execution (the
                    # batch merely held intra-batch duplicates): scatter
                    # through the inverse map and ride the normal tail —
                    # including the quality feed — exactly like the dedup
                    # path this plan subsumes.
                    host = {k: v[row_ctx.inverse] for k, v in host.items()}
                else:
                    # Mixed fresh/cached batch: delivery scatters device +
                    # cached + foreign-filled rows back into each
                    # request's slice (and may defer on still-in-flight
                    # foreign fills). The quality plane is deliberately
                    # skipped — the assembled vector mixes fresh and
                    # cache-served scores, and the plane's contract
                    # sketches only fresh ones (cache hits are excluded
                    # the same way).
                    self._finish_row_batch(group, row_ctx, host)
                    return
            screened: dict[int, str] = {}
            if integ is not None and integ.config.screen:
                # Readback sanity screen (ISSUE 20 layer 2): per-request
                # slices of the score output, post-widen/post-scatter —
                # the exact bytes delivery hands each waiter. A failing
                # ROW fails only its own request (the poisoned-input
                # per-item precedent); batchmates deliver normally.
                skey = group[0].servable.model.score_output
                sarr = host.get(skey)
                if sarr is not None:
                    soff = 0
                    for idx, it in enumerate(group):
                        row = sarr[soff : soff + it.n]
                        soff += it.n
                        if it.warmup:
                            continue
                        reason = integ.screen_reason(row)
                        if reason is not None:
                            screened[idx] = reason
                            integ.note_screen_trip(reason)
            q = self.quality  # capture: detachable mid-flight (bench A/B)
            if screened:
                # A batch with ANY screened row never feeds the quality
                # plane — the readback is suspect wholesale, and sketching
                # corrupt scores would poison the drift baselines.
                q = None
            if q is not None and meta is None:
                # Quality-plane feed, BEFORE the waiters unblock so a
                # drift exemplar's `quality.drift` annotation is already
                # on the span when the RPC handler finishes (and the tail
                # sampler force-keeps) it. Top-k-compacted batches (meta)
                # are excluded: topk_restore_host back-fills 0.0 off the
                # head, so the full vector is not the model's prediction
                # over the request — sketching it (or joining labels
                # against the synthetic zeros) would poison the
                # distribution, and sketching only the head would bias
                # it high by construction.
                try:
                    self._observe_quality(q, group, host)
                except Exception:  # noqa: BLE001 — the observability
                    pass           # plane must never fail a batch
            off = 0
            for idx, it in enumerate(group):
                sliced = {k: v[off : off + it.n] for k, v in host.items()}
                off += it.n
                try:
                    if it.future.cancelled():
                        continue
                    if idx in screened:
                        it.future.set_exception(IntegrityScreenError(
                            f"readback screen failed this request's rows: "
                            f"{screened[idx]}"
                        ))
                    else:
                        it.future.set_result(sliced)
                except InvalidStateError:
                    # A service-deadline cancel can land between the check
                    # and set_result; that waiter is gone, but its race must
                    # not poison co-batched requests via the except below.
                    pass
            if integ is not None:
                # Screen-trip burst -> recovery escalation, AFTER delivery:
                # the tripped rows already failed individually; the cycle
                # (trigger "output_corrupt") reinits the executor before
                # the next batch inherits the sick output path.
                integ.maybe_escalate_screen(self.recovery)
        except Exception as exc:
            if phases is not None:
                _replay_group_phases(group, phases)
            if row_ctx is not None:
                # Idempotent after a successful fill (the flights are
                # already popped); on a readback failure it fails the
                # foreign batches waiting on this batch's rows.
                row_ctx.abort(exc)
            rec = self.recovery  # capture: detachable mid-flight
            if rec is not None and rec.take_group(group, exc):
                # Device-fatal readback failure: the recovery plane owns
                # these items (replay resolves their futures).
                taken_by_recovery = True
            else:
                for it in group:
                    if not it.future.done():
                        it.future.set_exception(exc)
        finally:
            if util is not None:
                util.depth_dec()
            if run_token is not None and run_fn is not None:
                # Close the elastic per-split in-flight registration: THIS
                # is the drain barrier's release point — readback done (or
                # failed), the old split's batch is no longer in flight.
                try:
                    run_fn.note_complete(run_token)
                except Exception:  # noqa: BLE001 — accounting, never fatal
                    pass
            # Recycle the padded-batch buffers: the readback finished, so
            # the H2D upload that read them is long done — the only point
            # in the batch lifecycle where reuse is provably safe. The
            # EXCEPTION is a device-fatal failure the recovery plane took:
            # a lost/wedged device may still hold async references into
            # these host buffers, so they leak to GC (the _run_stage
            # failure-path precedent) — the _HostBufferRing recycle
            # contract extension the replay path relies on.
            if (
                self.buffer_ring is not None and ring_bufs
                and not taken_by_recovery
            ):
                self.buffer_ring.release(ring_bufs)
            # The breaker closes itself here: once the stuck (or healthy)
            # readback finishes, the wedge condition clears with it — and
            # any coalescer free-riding the busy pipeline (or a dispatch
            # thread waiting on the in-flight window) is woken, since
            # capacity just opened up.
            with self._cv:
                self._inflight_groups.pop(batch_id, None)
                if self._inflight.pop(batch_id, None) is not None:
                    left = self._inflight_buckets.get(bucket, 0) - 1
                    if left > 0:
                        self._inflight_buckets[bucket] = left
                    else:
                        self._inflight_buckets.pop(bucket, None)
                self._cv.notify_all()

    @staticmethod
    def _observe_quality(q, group: list[_WorkItem], host: dict) -> None:
        """Feed the quality plane one observation per non-warmup member
        request: the model's score output sliced per item EXACTLY like
        the result delivery below it (post-widen, post-dedup-scatter, so
        the sketched scores are the scores clients receive). Requests
        whose output filter dropped the score output contribute nothing
        — there is no score to sketch."""
        score_key = group[0].servable.model.score_output
        scores = host.get(score_key)
        if scores is None:
            return
        off = 0
        for it in group:
            s = scores[off : off + it.n]
            off += it.n
            if it.warmup:
                continue  # compile traffic is not a prediction signal
            q.observe(
                it.servable.name, it.servable.version, s,
                lane=it.criticality, span=it.span, arrays=it.arrays,
                trace_id=it.span.trace_id if it.span is not None else None,
            )
