"""Dynamic batching engine — the in-tree replacement for TF-Serving's
server-side batching (the reference claims it as a core capability,
README.md:5,9, but delegates it to the external tensorflow_model_server).

TPU-first design:

- **Padded candidate buckets.** XLA compiles one executable per input shape,
  so arbitrary candidate counts would cause a compile storm. Incoming work is
  padded up to a fixed bucket ladder (powers of two by default); jax.jit's
  own trace cache then keys on the bucket shape, giving exactly one compiled
  executable per (servable, bucket).
- **Request coalescing.** Concurrent small requests targeting the same
  (servable, signature) are concatenated along the candidate axis into one
  device call, then split back — amortizing dispatch overhead exactly like
  TF-Serving's BatchingSession. At low load a request waits at most
  `max_wait_us` before dispatch; under sustained load the window is
  *pipeline-aware*: while >= `pipeline_depth` batches are already in
  flight, dispatching another partial batch would only queue behind device
  work, so the batcher keeps filling past the deadline for free — latency
  is unchanged (the dispatch would have waited anyway) and occupancy rises
  toward full buckets.
- **Host-side id folding.** Wire ids are int64 (DCNClient.java:98-102) but
  jax runs x64-disabled; ids are folded into the vocab with int64 numpy on
  the host (exact `mod`, not truncation) before device transfer, which also
  shrinks the transfer 2x.

The core is a dedicated batching thread with a thread-safe queue, so it
serves both the sync grpc server (handler threads block on a Future) and the
asyncio server (await wrap_future). Device work is serialized in the batcher
thread — one stream of dispatches, no device-side contention.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading
import time
import weakref
from collections.abc import Callable
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError, ThreadPoolExecutor

import jax
import numpy as np

from ..models.base import Model
from ..models.registry import Servable
from ..ops.transfer import (
    combined_layout,
    combined_supported,
    pack_host,
    pack_host_combined,
    transfer_spec,
    unpack_device,
    unpack_device_combined,
)
from ..utils.tracing import request_trace

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096)

# Reusable (stateless) no-op context for the non-x64 hot path.
_NULL_CTX = contextlib.nullcontext()


class BatchTooLargeError(ValueError):
    pass


class QueueOverloadError(RuntimeError):
    """Queue admission refused: accepting more work would only build a
    backlog no deadline survives. Maps to RESOURCE_EXHAUSTED at the RPC
    layer — shedding beats queueing past the client's deadline."""


class DeviceWedgedError(RuntimeError):
    """Circuit breaker open: a dispatched batch has been stuck past the
    wedge threshold, so the device (or its compile path) is presumed hung.
    New work fails fast (UNAVAILABLE) instead of burning a handler thread
    per request for the full RPC deadline; the breaker closes by itself the
    moment the stuck batch completes."""


def bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise BatchTooLargeError(f"candidate count {n} exceeds largest bucket {buckets[-1]}")


def fold_ids_host(ids: np.ndarray, vocab_size: int) -> np.ndarray:
    """Exact int64 modulo fold on the host; models re-fold idempotently.
    Delegates to the one canonical fold (native.fold_ids) shared with the
    client's compact_payload."""
    from .. import native

    return native.fold_ids(ids, vocab_size)


def _immutably_backed(arr: np.ndarray) -> bool:
    """True only when the array's ULTIMATE buffer is a `bytes` object —
    the one backing genuinely immutable to every party (the serving path's
    np.frombuffer(proto.tensor_content) views). writeable=False alone is
    NOT enough: a frozen view over a writable base (broadcast_to,
    setflags(write=False)) can still see its bytes change under it, and
    even a read-only memoryview does not freeze its underlying bytearray/
    mmap — its owner can keep writing through the original object."""
    a = arr
    while isinstance(a.base, np.ndarray):
        a = a.base
    b = a.base
    if isinstance(b, memoryview):
        b = b.obj
    return isinstance(b, bytes)


def prepare_inputs(
    model: Model, arrays: dict[str, np.ndarray], fold_ids: bool = True
) -> dict[str, np.ndarray]:
    """Host-side normalization before padding/transfer.

    Every output array is OWNED or IMMUTABLE (never writable-aliased to the
    caller): submit() returns before the batch is padded/uploaded, so a
    caller mutating its array after submit() would race the async device
    transfer — and poison the content-addressed DeviceInputCache digest
    (round-1 advisor finding). fold/astype copy as a side effect; the
    passthrough branch skips the copy only for arrays whose backing buffer
    is itself immutable — the serving hot path's arrays are np.frombuffer
    views over protobuf bytes, which NOBODY can mutate (~50 us per 1k x 43
    request back on the 1-core host); anything else is copied.

    fold_ids=False defers the vocab fold to batch time (_execute folds the
    whole padded batch in ONE native call): per-request folding charged
    ~130 us of ctypes+alloc overhead per 1k-candidate request to the RPC
    thread/event loop — at 500 QPS that is ~7% of the single-core budget —
    while the batched fold costs the batcher thread ~150 us per 8k batch,
    GIL released. Callers that apply the model directly on the returned
    arrays (tests, measurement harnesses) keep the folding default: unfolded
    int64 would be silently int32-cast by device_put under x64-disabled
    JAX and re-fold into garbage for ids past 2^31."""
    out = {}
    for key, arr in arrays.items():
        if key == "feat_ids" and fold_ids and model.folds_ids_on_host:
            out[key] = fold_ids_host(arr, model.config.vocab_size)
        elif arr.dtype == np.float64 and not model.needs_x64:
            # Convenience downcast for the 32-bit zoo path only: an x64
            # model (graph executor with DT_DOUBLE inputs) must see the
            # doubles it was exported with.
            out[key] = arr.astype(np.float32)
        elif _immutably_backed(arr):
            out[key] = arr
        else:
            out[key] = arr.copy()
    return out


class DeviceInputCache:
    """Content-addressed LRU of device-resident input arrays.

    The serving hot path is host->device upload bound: a padded batch is
    ~0.2 KB/candidate and the link (PCIe, or this rig's relay tunnel) is the
    slowest hop in the stack. CTR traffic re-scores the same hot candidate
    sets continuously (the reference's own benchmark re-sends one payload for
    all 6,000 requests, DCNClient.java:208-210), so identical batch bytes
    recur. Keying the *device* array by a content digest of the packed host
    bytes lets a repeat batch skip the upload entirely — the jitted call gets
    an argument that is already resident in HBM.

    Misses cost one content digest (~0.1 ms/MB native, ~1.5 ms/MB blake2b
    fallback) plus the device_put the dispatch needed anyway; hits cost only
    the digest. Capacity is bounded by entry count (batches are ~1 MB;
    default 64 entries ~ 64 MB of a v5e's 16 GB HBM) with least-recently-used
    eviction.

    Traffic that never repeats would pay the digest for nothing, so the
    cache self-disables — and re-probes: the hit rate is tracked over a
    SLIDING window of `probe_window` lookups (not the process lifetime —
    a unique-traffic phase after a long repeated phase must still flip to
    pass-through, round-3 weak #3: the one-shot probe never fired because
    global hit rate stayed high). When a window's rate is below
    `min_hit_rate`, hashing stops; after `reprobe_every` bypassed lookups
    the cache re-enters probing so a traffic regime that turns repetitive
    again re-engages it (probing costs one window of digests per
    `reprobe_every` lookups, ~12% of digest cost while traffic stays
    unique).
    """

    def __init__(
        self,
        max_entries: int = 64,
        # 64-lookup windows: repeated traffic hits ~100% so false bypass
        # needs a 63/64-miss window (won't happen), while a unique phase
        # is detected within ~64 batches; reprobe_every=512 caps probing
        # overhead at ~11% of digest cost during sustained-unique traffic
        # and bounds regime-flip recovery to ~576 batches (~15 s at the
        # rig's batch cadence).
        probe_window: int = 64,
        min_hit_rate: float = 0.02,
        reprobe_every: int = 512,
    ):
        self.max_entries = max_entries
        self.probe_window = probe_window
        self.min_hit_rate = min_hit_rate
        self.reprobe_every = reprobe_every
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.bytes_skipped = 0
        self.bypassed = False
        self.bypass_cycles = 0
        self._win_hits = 0
        self._win_lookups = 0
        self._bypassed_lookups = 0

    def rearm(self) -> None:
        """Exit bypass immediately and restart the probe cycle — for
        callers that KNOW a traffic-regime boundary just happened (a bench
        phase change, a deployment cutover) and should not wait out the
        automatic re-probe cadence. One locked reset of the full counter
        set so external callers cannot drift from _note_bypassed's own
        re-arm sequence."""
        with self._lock:
            self.bypassed = False
            self._bypassed_lookups = 0
            self._win_hits = 0
            self._win_lookups = 0

    def _note_bypassed(self) -> None:
        """Count a pass-through lookup; periodically re-enter probing."""
        with self._lock:
            self._bypassed_lookups += 1
            if self._bypassed_lookups >= self.reprobe_every:
                self._bypassed_lookups = 0
                self._win_hits = 0
                self._win_lookups = 0
                self.bypassed = False

    @staticmethod
    def _key(name: str, arr: np.ndarray) -> tuple:
        from .. import native

        if native.available():
            digest = native.hash128(arr)  # ~5x blake2b, GIL released
        else:
            # uint8 view: ml_dtypes (bf16) arrays refuse the buffer
            # protocol directly ("cannot include dtype 'E'"), and the
            # digest is over raw bytes anyway.
            digest = hashlib.blake2b(
                np.ascontiguousarray(arr).view(np.uint8).data, digest_size=16
            ).digest()
        return (name, arr.shape, arr.dtype.str, digest)

    def get_or_put(
        self,
        name: str,
        arr: np.ndarray,
        pack: Callable[[np.ndarray], np.ndarray] | None = None,
        pack_tag: str = "",
    ) -> jax.Array | np.ndarray:
        """Device array for `arr`'s content, uploading (after `pack`, when
        given) only on miss. The digest keys on the PRE-pack bytes so a hit
        skips the transfer-compression work too. `pack` must be pure and
        `pack_tag` must identify the transform: the stored value is
        POST-pack, so the same raw bytes packed differently must occupy
        distinct entries."""
        if self.bypassed:
            self._note_bypassed()
            return pack(arr) if pack is not None else arr  # plain jit path
        key = (pack_tag, *self._key(name, arr))
        return self._lookup(key, lambda: pack(arr) if pack is not None else arr)

    def get_or_put_group(
        self,
        arrays: dict[str, np.ndarray],
        build: Callable[[], np.ndarray],
        tag: str,
    ) -> jax.Array | np.ndarray:
        """Device buffer for a GROUP of arrays (the combined-transfer path):
        keyed on every member's content digest plus `tag` (the layout), so a
        hit skips pack+concat+upload in one lookup. `build()` produces the
        combined host buffer only on miss."""
        if self.bypassed:
            self._note_bypassed()
            return build()
        key = (tag,) + tuple(self._key(k, arrays[k]) for k in sorted(arrays))
        return self._lookup(key, build)

    def _lookup(self, key: tuple, build_host: Callable[[], np.ndarray]):
        """Shared LRU hit/miss core: one implementation of the accounting,
        eviction, and the adaptive-bypass probe."""
        with self._lock:
            cached = self._lru.get(key)
            if cached is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                self._win_hits += 1
                self._close_window_locked()
                # The avoided upload is the stored (post-pack) size.
                self.bytes_skipped += cached.nbytes
                return cached
        device_arr = jax.device_put(build_host())  # async; the executable waits, not us
        with self._lock:
            self._lru[key] = device_arr
            self.misses += 1
            self._close_window_locked()
            while len(self._lru) > self.max_entries:
                self._lru.popitem(last=False)
        return device_arr

    def _close_window_locked(self) -> None:
        """Advance the sliding probe window; flip to bypass on a cold one.
        Caller holds _lock."""
        self._win_lookups += 1
        if self._win_lookups < self.probe_window:
            return
        if self._win_hits < self._win_lookups * self.min_hit_rate:
            self.bypassed = True
            self.bypass_cycles += 1
            self._bypassed_lookups = 0
            self._lru.clear()
        self._win_hits = 0
        self._win_lookups = 0

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()


@dataclasses.dataclass
class _WorkItem:
    servable: Servable
    arrays: dict[str, np.ndarray]  # host arrays, candidate-major
    n: int
    future: Future  # resolves to dict[str, np.ndarray]
    enqueue_t: float
    output_keys: tuple[str, ...] | None  # None = all model outputs
    # Warmup work legitimately spends minutes compiling on the batcher
    # thread; it must not read as a wedged device to the circuit breaker.
    warmup: bool = False


@dataclasses.dataclass
class BatcherStats:
    """Occupancy/queueing gauges (SURVEY.md §5 metrics obligations)."""

    batches: int = 0
    requests: int = 0
    candidates: int = 0
    padded_candidates: int = 0
    # Batches assembled by the native fused pack (hostops.cc
    # pack_batch_u24_bf16: fold+u24+bf16+pad+concat in one pass per input
    # instead of 4 python/numpy passes + 3 temporaries).
    fused_batches: int = 0
    max_queue_depth: int = 0
    # Times coalescing waited past max_wait because the dispatch pipeline
    # was saturated (the wait was latency-free; see _coalesce_next).
    fill_waits: int = 0

    @property
    def mean_occupancy(self) -> float:
        return self.candidates / self.padded_candidates if self.padded_candidates else 0.0

    @property
    def mean_requests_per_batch(self) -> float:
        return self.requests / self.batches if self.batches else 0.0


class DynamicBatcher:
    """Queue + batching thread + per-bucket jit cache.

    run_fn(servable, batch) -> outputs is injected so the parallel layer can
    swap in a sharded executor (pjit over a mesh) without touching batching
    logic; the default executes servable.model.apply under jax.jit.
    """

    def __init__(
        self,
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_wait_us: int = 200,
        max_batch_candidates: int | None = None,
        run_fn: Callable | None = None,
        completion_workers: int = 4,
        compress_transfer: bool = True,
        input_cache_entries: int = 64,
        queue_capacity_candidates: int | None = None,
        breaker_timeout_s: float | None = 90.0,
        pipeline_depth: int = 2,
    ):
        self.compress_transfer = compress_transfer
        # Content-addressed device-resident inputs (only meaningful for the
        # default jit path; a custom run_fn manages its own placement).
        self.input_cache = (
            DeviceInputCache(input_cache_entries)
            if input_cache_entries and run_fn is None
            else None
        )
        self.buckets = tuple(sorted(buckets))
        self.max_wait_s = max_wait_us / 1e6
        # Clamped: coalescing past the largest bucket would build a batch no
        # bucket can hold and fail the whole group at dispatch time.
        self.max_batch_candidates = min(
            max_batch_candidates or self.buckets[-1], self.buckets[-1]
        )
        # Admission bound: at most this many candidates queued (not yet
        # dispatched). 16 full max-size batches of backlog is already several
        # deadlines' worth of work; past that, shedding with
        # RESOURCE_EXHAUSTED is strictly kinder than queueing.
        # Clamped to at least one full max-size batch: a capacity below
        # buckets[-1] would permanently reject every request larger than it
        # even on an idle queue.
        self.queue_capacity_candidates = max(
            queue_capacity_candidates
            if queue_capacity_candidates is not None
            else 16 * self.buckets[-1],
            self.buckets[-1],
        )
        # Wedge threshold for the circuit breaker. Default is above any sane
        # steady-state batch but below the 120s RPC deadline; first compiles
        # belong in warmup(), not live traffic.
        self.breaker_timeout_s = breaker_timeout_s
        # Coalescing keeps filling past max_wait while this many batches are
        # in flight: one executing on device plus one queued behind it means
        # an extra dispatch cannot start sooner anyway, so waiting is free.
        # Depth 1 would serialize dispatch against readback (killing the
        # pipeline at low load); below 2 is therefore clamped.
        self.pipeline_depth = max(pipeline_depth, 2)
        self._items: "deque[_WorkItem]" = deque()
        self._cv = threading.Condition()
        self._queued_candidates = 0
        # Wedge bookkeeping: wall-clock starts of (a) the dispatch currently
        # on the batcher thread and (b) every readback in flight.
        self._dispatching_since: float | None = None
        self._inflight: dict[int, float] = {}
        self._inflight_seq = 0
        # Weak keys: unloaded servables must not pin their compiled
        # executables, and a recycled object address must not serve a stale
        # one (Servable uses eq=False, so it is hashable and weakref-able).
        self._jitted: weakref.WeakKeyDictionary[Servable, tuple[Callable, dict]] = (
            weakref.WeakKeyDictionary()
        )
        self._run_fn = run_fn
        self.stats = BatcherStats()
        self._thread = threading.Thread(target=self._loop, name="batcher", daemon=True)
        self._started = False
        self._stopping = False
        # Device->host readback happens off the batching thread so batch k+1's
        # transfer+compute dispatch overlaps batch k's result fetch — this is
        # what pipelines over host<->device link latency (jax dispatch is
        # async; only the fetch blocks). Several workers = several batches'
        # readbacks in flight.
        self._completers = ThreadPoolExecutor(
            max_workers=completion_workers, thread_name_prefix="batch-complete"
        )

    # ------------------------------------------------------------------ API

    def start(self) -> "DynamicBatcher":
        if not self._started:
            self._started = True
            self._thread.start()
            # Compile/load the native host ops off-thread so the first
            # request never pays the g++ latency (numpy fallback until ready).
            from .. import native

            native.warm_async()
        return self

    def stop(self) -> None:
        if self._started:
            with self._cv:
                self._stopping = True
                self._cv.notify_all()
            self._thread.join(timeout=5)
            self._completers.shutdown(wait=True)
            self._started = False

    def _wedged_for(self, now: float) -> float:
        """Seconds the oldest stuck batch has been in flight past the
        breaker threshold; 0.0 when healthy. Caller holds _cv."""
        t = self.breaker_timeout_s
        if t is None:
            return 0.0
        worst = 0.0
        if self._dispatching_since is not None:
            worst = now - self._dispatching_since
        for t0 in self._inflight.values():
            worst = max(worst, now - t0)
        return worst if worst > t else 0.0

    def _shed_queued(self, exc: Exception) -> None:
        """Fail every queued (not yet dispatched) item. Caller holds _cv."""
        while self._items:
            it = self._items.popleft()
            self._queued_candidates -= it.n
            if not it.future.done():
                it.future.set_exception(exc)

    def submit(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        output_keys: tuple[str, ...] | None = None,
        _warmup: bool = False,
    ) -> Future:
        """Enqueue one request's arrays; returns a Future of output arrays
        (sliced back to the request's own candidate count). output_keys limits
        which model outputs are fetched back to the host.

        Admission control (SURVEY.md §5 failure-detection obligations): a
        wedged device fails the request immediately (DeviceWedgedError, and
        the backlog is shed with it), and a backlog past
        queue_capacity_candidates is refused (QueueOverloadError) instead of
        queueing work no deadline survives."""
        if self._stopping:
            raise RuntimeError("batcher is stopped")
        ns = {k: v.shape[0] for k, v in arrays.items()}
        n = next(iter(ns.values()))
        if any(v != n for v in ns.values()):
            raise ValueError(f"inconsistent candidate counts across inputs: {ns}")
        bucket_for(n, self.buckets)  # validate size up front, raises if too big
        # Admission BEFORE the defensive copy: a shed request must not pay
        # the copy/fold cost — overload is exactly when the host can least
        # afford it. Capacity is reserved under the lock so concurrent
        # submits cannot overshoot while this one prepares its arrays.
        with self._cv:
            stuck_s = self._wedged_for(time.perf_counter())
            if stuck_s:
                exc = DeviceWedgedError(
                    f"a dispatched batch has been stuck {stuck_s:.1f}s "
                    f"(> breaker {self.breaker_timeout_s:.0f}s); failing fast"
                )
                self._shed_queued(exc)
                raise exc
            if self._queued_candidates + n > self.queue_capacity_candidates:
                raise QueueOverloadError(
                    f"queue holds {self._queued_candidates} candidates; admitting "
                    f"{n} more would exceed capacity {self.queue_capacity_candidates}"
                )
            self._queued_candidates += n
        fut: Future = Future()
        try:
            item = _WorkItem(
                servable=servable,
                arrays=prepare_inputs(servable.model, arrays, fold_ids=False),
                n=n,
                future=fut,
                enqueue_t=time.perf_counter(),
                output_keys=output_keys,
                warmup=_warmup,
            )
        except BaseException:
            with self._cv:
                self._queued_candidates -= n
            raise
        with self._cv:
            self._items.append(item)
            self.stats.max_queue_depth = max(self.stats.max_queue_depth, len(self._items))
            self._cv.notify()
        return fut

    @staticmethod
    def warmup_arrays(servable: Servable, n: int) -> dict[str, np.ndarray]:
        """Zero batch matching the servable's default-signature inputs —
        signature-driven so optional inputs (DLRM dense_features) are
        included and imported signatures warm what they actually declare."""
        from .. import codec

        sig = servable.signature("")
        out = {}
        for spec in sig.inputs:
            if spec.shape is None or len(spec.shape) < 1:
                continue  # unknown rank: nothing sensible to synthesize
            dims = (n,) + tuple(d or 1 for d in spec.shape[1:])
            out[spec.name] = np.zeros(dims, codec.dtype_to_numpy(spec.dtype))
        return out

    def warmup(self, servable: Servable, buckets: tuple[int, ...] | None = None) -> None:
        """Precompile the bucket ladder for a servable (compile storms belong
        at load time, not first-request time). Executes directly — only safe
        before the batcher serves traffic; once live, use warmup_via_queue."""
        for b in buckets or self.buckets:
            self._execute(servable, prepare_inputs(servable.model, self.warmup_arrays(servable, b)))

    def warmup_via_queue(
        self, servable: Servable, buckets: tuple[int, ...] | None = None
    ) -> None:
        """Warm a servable THROUGH the request queue: compilation happens on
        the batching thread exactly like live traffic, so hot-loading a new
        model version never races the jit caches with in-flight requests."""
        futures = [
            self.submit(servable, self.warmup_arrays(servable, b), _warmup=True)
            for b in buckets or self.buckets
        ]
        for fut in futures:
            fut.result(timeout=600)

    def jit_entry(self, servable: Servable) -> tuple[Callable, dict[str, str], bool]:
        """The (jitted fn, transfer spec, combined) this batcher serves
        `servable` with — public so measurement harnesses (bench.py's
        device-limited decomposition) can time the EXACT serving executable,
        warm caches included, instead of compiling a lookalike. When
        `combined` is True the fn signature is (params, uint8_buffer,
        layout) with layout static (ops/transfer.py combined_layout)."""
        return self._jit_for(servable)

    # ------------------------------------------------------------- internals

    def _jit_for(self, servable: Servable) -> tuple[Callable, dict[str, str], bool]:
        entry = self._jitted.get(servable)
        if entry is None:
            spec = transfer_spec(servable.model) if self.compress_transfer else {}
            apply = servable.model.apply
            combined = self.compress_transfer and not servable.model.needs_x64
            if combined:
                # One uint8 buffer per batch = ONE host->device transfer
                # instead of one per input; the layout split + bitcasts are
                # traced into the executable and fuse with consumers.
                # (x64 models keep the per-key path: their int64 inputs
                # must cross the boundary as int64, not raw bytes plus an
                # in-graph bitcast that enable_x64 scoping complicates.)
                #
                # The layout is CLOSED OVER per distinct layout (a couple
                # per servable — it is bucket-independent metadata) instead
                # of riding static_argnums: hashing that nested tuple on
                # every call cost ~175 us/batch of pure dispatch overhead
                # (round-4 microbench: 426 -> 251 us/call arg processing),
                # and the inner jit cache keys on buffer shape exactly as
                # before.
                layout_fns: dict[tuple, Callable] = {}

                def fn(params, buf, layout, _apply=apply, _cache=layout_fns):
                    jfn = _cache.get(layout)
                    if jfn is None:
                        jfn = _cache[layout] = jax.jit(
                            lambda p, b, _l=layout: _apply(
                                p, unpack_device_combined(b, _l)
                            )
                        )
                    return jfn(params, buf)
            elif spec:
                # Transfer decompression is traced into the executable, so it
                # fuses with the embedding lookup's index arithmetic.
                fn = jax.jit(lambda params, packed: apply(params, unpack_device(packed, spec)))
            else:
                fn = jax.jit(apply)
            if servable.model.needs_x64:
                # Trace AND call inside enable_x64: graph-executor models
                # (interop/graph_exec.py) carry int64 feature ids that the
                # default 32-bit canonicalization would silently truncate at
                # the jit boundary — before the graph's own hashing/mod runs.
                base = fn

                def fn(params, batch, _base=base):
                    with jax.enable_x64():
                        return _base(params, batch)

            entry = (fn, spec, combined)
            self._jitted[servable] = entry
        return entry

    _FUSED_SPEC = {"feat_ids": "u24", "feat_wts": "bf16"}

    def _try_execute_fused(self, group: list[_WorkItem], bucket: int):
        """Dispatch via the native fused batch assembler when the group fits
        the flagship combined layout; None = caller runs the generic path.

        hostops.cc pack_batch_u24_bf16 reads each request's arrays once and
        writes the final padded [u24 ids | bf16 wts] device buffer directly
        — the generic path makes 4 full passes (pad copy, fold, pack,
        concat) with 3 temporaries per batch (~1.25 ms/batch at the 16k
        bucket on this host, round-3 phases). The buffer is bit-identical
        to pack_host_combined over the padded batch (pinned by
        tests/test_batcher.py), so it shares the same compiled executables
        and the same content-cache semantics (keyed per-part here; distinct
        tag keeps the two key schemes apart)."""
        import os

        import ml_dtypes

        from .. import native

        servable = group[0].servable
        model = servable.model
        if (
            self._run_fn is not None
            or not self.compress_transfer
            or model.needs_x64
            or not model.folds_ids_on_host
            or os.environ.get("DTS_TPU_NO_FUSED") == "1"  # A/B isolation knob
            or not native.available()
        ):
            return None
        fn, spec, combined = self._jit_for(servable)
        if not combined or spec != self._FUSED_SPEC:
            return None
        first = group[0].arrays
        if set(first) != {"feat_ids", "feat_wts"}:
            return None
        fields = first["feat_ids"].shape[1] if first["feat_ids"].ndim == 2 else None
        if not fields:
            return None
        for it in group:
            ids, wts = it.arrays["feat_ids"], it.arrays["feat_wts"]
            if (
                ids.ndim != 2 or ids.shape[1] != fields
                or wts.shape != ids.shape
                or ids.dtype not in (np.int64, np.int32)
                or wts.dtype not in (np.float32, ml_dtypes.bfloat16)
            ):
                return None
        layout = combined_layout(
            {k: first[k] for k in ("feat_ids", "feat_wts")}, spec
        )
        vocab = model.config.vocab_size
        ids_parts = [it.arrays["feat_ids"] for it in group]
        wts_parts = [it.arrays["feat_wts"] for it in group]

        def build():
            return native.pack_batch_u24_bf16(
                ids_parts, wts_parts, fields, bucket, vocab
            )

        # One span scope matching the generic path's batch.dispatch (which
        # wraps _execute = cache+pack+jitcall), so fused/generic phase
        # decompositions compare like for like; opened only after
        # eligibility so an ineligible probe costs the stats nothing.
        with request_trace.span("batch.dispatch"):
            cache = self.input_cache
            if cache is not None and not cache.bypassed:
                with request_trace.span("batch.cache"):
                    # Per-part content digests (same digest primitive, same
                    # total bytes as the group digest) + padded geometry.
                    # vocab is IN the tag: the digests are over RAW ids,
                    # and the stored buffer's fold depends on it — two
                    # servables sharing a batcher but not a vocab must
                    # never share entries (review finding; the generic
                    # path's digests are post-fold so it gets this free).
                    key = (
                        (f"fused:{layout}:{bucket}:{vocab}",)
                        + tuple(cache._key("i", a) for a in ids_parts)
                        + tuple(cache._key("w", a) for a in wts_parts)
                    )
                    buf = cache._lookup(key, build)
            else:
                if cache is not None:
                    cache._note_bypassed()
                with request_trace.span("batch.fusedpack"):
                    buf = build()
            with request_trace.span("batch.jitcall"):
                return fn(servable.params, buf, layout)

    def _execute(self, servable: Servable, arrays: dict[str, np.ndarray]):
        ids = arrays.get("feat_ids")
        if ids is not None and ids.dtype == np.int64 and servable.model.folds_ids_on_host:
            # Deferred per-request fold (prepare_inputs fold_ids=False):
            # one native fold over the whole padded batch. Runs BEFORE the
            # content digest, so cache keys are over the same folded bytes
            # as the eager-fold path produced.
            arrays = dict(arrays)
            arrays["feat_ids"] = fold_ids_host(ids, servable.model.config.vocab_size)
        if self._run_fn is not None:
            return self._run_fn(servable, arrays)
        fn, spec, combined = self._jit_for(servable)
        if combined and not combined_supported(arrays):
            # Rare servable whose inputs cannot ride a byte buffer (string/
            # bool/8-byte tensors): rebuild the per-key entry once and pin
            # it (same spec — only the transfer packaging changes).
            apply = servable.model.apply
            fn = jax.jit(
                lambda params, packed: apply(params, unpack_device(packed, spec))
            ) if spec else jax.jit(apply)
            self._jitted[servable] = (fn, spec, False)
            combined = False
        # x64 models need the context around the UPLOADS too: device_put
        # (inside the input cache) canonicalizes, and an int64 batch put
        # outside the context reaches the x64-traced executable as int32.
        ctx = jax.enable_x64() if servable.model.needs_x64 else _NULL_CTX
        with ctx:
            if combined:
                layout = combined_layout(arrays, spec)
                if self.input_cache is not None:
                    # Digest the RAW arrays (a content hit skips pack AND
                    # concat AND upload); layout in the tag keeps distinct
                    # packings of identical bytes apart.
                    with request_trace.span("batch.cache"):
                        buf = self.input_cache.get_or_put_group(
                            arrays,
                            build=lambda: pack_host_combined(arrays, spec),
                            tag=str(layout),
                        )
                else:
                    buf = pack_host_combined(arrays, spec)
                with request_trace.span("batch.jitcall"):
                    return fn(servable.params, buf, layout)
            if self.input_cache is not None:
                # Digest BEFORE packing: a content hit skips both the upload
                # and the pack (u24/bf16) work.
                with request_trace.span("batch.cache"):
                    inputs = {
                        k: self.input_cache.get_or_put(
                            k, v,
                            pack=(lambda a, _k=k: pack_host({_k: a}, spec)[_k]) if spec else None,
                            pack_tag=spec.get(k, "") if spec else "",
                        )
                        for k, v in arrays.items()
                    }
                with request_trace.span("batch.jitcall"):
                    return fn(servable.params, inputs)
            packed = pack_host(arrays, spec) if spec else arrays
            with request_trace.span("batch.jitcall"):
                return fn(servable.params, packed)

    def _take(self) -> _WorkItem | None:
        """Pop the next live queued item, blocking; None on shutdown after
        the queue drains (every accepted item is still served)."""
        with self._cv:
            while True:
                while self._items:
                    it = self._items.popleft()
                    self._queued_candidates -= it.n
                    if it.future.cancelled():
                        continue  # waiter gave up (RPC deadline); skip the work
                    return it
                if self._stopping:
                    return None
                self._cv.wait()

    def _coalesce_next(self, item: _WorkItem, total: int, deadline: float) -> _WorkItem | None:
        """Next same-target item within the (pipeline-extended) window, or
        None. The head item stays put when it doesn't match — deque order is
        preserved (the old SimpleQueue requeue pushed it to the BACK,
        reordering traffic).

        Past `deadline` the wait continues only while the dispatch pipeline
        is saturated (>= pipeline_depth batches in flight and none wedged):
        the next dispatch would queue behind device work regardless, so the
        extra fill time costs no latency. Completion of any in-flight batch
        notifies this wait, ending the free-ride the moment dispatch could
        actually start."""
        free_ride_counted = False
        with self._cv:
            while True:
                while not self._items:
                    now = time.perf_counter()
                    if self._stopping:
                        return None
                    if now < deadline:
                        self._cv.wait(deadline - now)
                        continue
                    if len(self._inflight) < self.pipeline_depth or self._wedged_for(now):
                        return None
                    # Free-riding the busy pipeline; a completion notifies.
                    # Bounded wait: the wedge clock advances with wall time
                    # alone, so never sleep unboundedly on the condition.
                    # Counted once per episode, not per poll iteration.
                    if not free_ride_counted:
                        self.stats.fill_waits += 1
                        free_ride_counted = True
                    self._cv.wait(0.005)
                nxt = self._items[0]
                if nxt.future.cancelled():
                    self._items.popleft()
                    self._queued_candidates -= nxt.n
                    continue
                if (
                    nxt.servable is item.servable
                    and nxt.arrays.keys() == item.arrays.keys()
                    and total + nxt.n <= self.max_batch_candidates
                ):
                    self._items.popleft()
                    self._queued_candidates -= nxt.n
                    return nxt
                return None

    def _loop(self) -> None:
        while True:
            item = self._take()
            if item is None:
                return
            group = [item]
            total = item.n
            deadline = item.enqueue_t + self.max_wait_s
            # Coalesce same-servable work until the deadline or size cap.
            while total < self.max_batch_candidates:
                nxt = self._coalesce_next(item, total, deadline)
                if nxt is None:
                    break
                group.append(nxt)
                total += nxt.n
            self._dispatch(group, total)

    def _dispatch(self, group: list[_WorkItem], total: int) -> None:
        with self._cv:
            # An all-warmup group is exempt from the wedge clock: hot-load
            # warmup (warmup_via_queue during a version rollout) legitimately
            # compiles for minutes on this thread, and tripping the breaker
            # then would shed live traffic during every rollout. A live
            # request coalesced into the group re-arms the clock.
            self._dispatching_since = (
                None if all(it.warmup for it in group) else time.perf_counter()
            )
        try:
            bucket = bucket_for(total, self.buckets)
            first = group[0]
            outputs = self._try_execute_fused(group, bucket)
            if outputs is not None:
                self.stats.fused_batches += 1
            else:
                keys = list(first.arrays.keys())
                batched = {}
                with request_trace.span("batch.pad"):
                    for k in keys:
                        parts = [it.arrays[k] for it in group]
                        if len(parts) == 1 and parts[0].shape[0] == bucket:
                            # Safe to pass through uncopied: prepare_inputs
                            # guarantees item arrays never alias caller buffers.
                            batched[k] = parts[0]
                            continue
                        # Single allocation + one copy per part (no concat temporaries).
                        # Mixed dtypes (an int64 wire request coalesced with a
                        # pre-folded int32 direct submit) widen, never wrap.
                        dt = parts[0].dtype
                        if any(p.dtype != dt for p in parts):
                            dt = np.result_type(*(p.dtype for p in parts))
                        out = np.empty((bucket,) + parts[0].shape[1:], dt)
                        off = 0
                        for p in parts:
                            out[off : off + p.shape[0]] = p
                            off += p.shape[0]
                        out[off:] = 0  # padding rows
                        batched[k] = out
                with request_trace.span("batch.dispatch"):
                    outputs = self._execute(first.servable, batched)  # async dispatch

            # Union of the group's wanted outputs; None on any item = all.
            wanted: set[str] | None = set()
            for it in group:
                if it.output_keys is None:
                    wanted = None
                    break
                wanted.update(it.output_keys)
            fetch = {
                k: v for k, v in outputs.items() if wanted is None or k in wanted
            }
            for v in fetch.values():
                # Start the device->host readback now; the completer thread
                # then finds the bytes already (or sooner) on host.
                if hasattr(v, "copy_to_host_async"):
                    v.copy_to_host_async()

            self.stats.batches += 1
            self.stats.requests += len(group)
            self.stats.candidates += total
            self.stats.padded_candidates += bucket

            # Readback + distribution off-thread: the batching thread moves on
            # to the next batch immediately, pipelining device work. The batch
            # is registered in-flight first so a readback that never returns
            # is visible to the circuit breaker.
            with self._cv:
                self._inflight_seq += 1
                batch_id = self._inflight_seq
                if not all(it.warmup for it in group):
                    self._inflight[batch_id] = time.perf_counter()
                # Wedge accounting moves from "dispatching" to "in flight"
                # atomically. Clearing only in the finally below would leave
                # a window where the completer has already resolved this
                # batch's futures while _dispatching_since still shows the
                # dispatch start — a submit racing that window would read a
                # long-finished dispatch as a wedged device.
                self._dispatching_since = None
            self._completers.submit(self._complete, batch_id, group, fetch)
        except Exception as exc:  # propagate to every waiter, keep serving
            for it in group:
                if not it.future.done():
                    it.future.set_exception(exc)
        finally:
            with self._cv:
                self._dispatching_since = None

    def _complete(self, batch_id: int, group: list[_WorkItem], outputs) -> None:
        try:
            with request_trace.span("batch.readback"):
                host = {k: np.asarray(v) for k, v in outputs.items()}
            off = 0
            for it in group:
                sliced = {k: v[off : off + it.n] for k, v in host.items()}
                off += it.n
                try:
                    if not it.future.cancelled():
                        it.future.set_result(sliced)
                except InvalidStateError:
                    # A service-deadline cancel can land between the check
                    # and set_result; that waiter is gone, but its race must
                    # not poison co-batched requests via the except below.
                    pass
        except Exception as exc:
            for it in group:
                if not it.future.done():
                    it.future.set_exception(exc)
        finally:
            # The breaker closes itself here: once the stuck (or healthy)
            # readback finishes, the wedge condition clears with it — and
            # any coalescer free-riding the busy pipeline is woken, since
            # dispatch capacity just opened up.
            with self._cv:
                self._inflight.pop(batch_id, None)
                self._cv.notify_all()
