"""Model-warmup request replay (TF-Serving's assets.extra convention).

`tensorflow_model_server` warms a newly loaded version by replaying the
PredictionLog records in `<version>/assets.extra/tf_serving_warmup_requests`
(a TFRecord file) before the version starts serving — so the first real
request never pays compilation or cold-cache cost, using the PRODUCER'S
OWN representative requests rather than synthetic shapes. This module
gives imported SavedModels the same treatment: the version watcher (and
`import_savedmodel` callers) replay the file through the real service
implementation against the real batcher, warming exactly the executables
and transfer layouts live traffic will hit.

File format: standard TFRecord framing — per record, a little-endian
uint64 length, the masked CRC32C of those 8 length bytes, the payload,
and the payload's masked CRC32C. CRC32C (Castagnoli) is implemented here
(pure Python, table-driven): warmup files are small, and validating the
checksums catches truncated writers — TF-Serving fails the load on a
corrupt warmup file, and so do we (WarmupError names the record).

Replay semantics match upstream: every log type replays through its RPC's
code path; the record's model_spec is OVERRIDDEN to target the version
being loaded (upstream replays against the just-loaded bundle regardless
of what name/version the producer recorded). A response embedded in the
log is ignored — warmup is about execution, not assertion. Upstream caps
the file at 1000 records; same cap here, same error.
"""

from __future__ import annotations

import pathlib
import struct

from ..models.registry import Servable, ServableRegistry

WARMUP_DIRNAME = "assets.extra"
WARMUP_FILENAME = "tf_serving_warmup_requests"
MAX_WARMUP_RECORDS = 1000  # upstream WarmupConsts::kMaxNumRecords


class WarmupError(RuntimeError):
    """Corrupt/oversized warmup file or a failing warmup request."""


# ------------------------------------------------------------------ crc32c


def _build_crc_table() -> list[int]:
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


# Built EAGERLY at import: the old lazy appender raced concurrent first
# callers (request-log writer thread vs. warmup replay) — one thread could
# read a partially filled table and CRC garbage (ADVICE round 5). A single
# module-level assignment of a fully built list is safe to publish.
_CRC_TABLE: list[int] = _build_crc_table()


def _crc_table() -> list[int]:
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord's masked CRC (avoids CRC-of-CRC pathologies)."""
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------------- tfrecord

def read_tfrecords(path):
    """Yield record payloads, validating framing and checksums."""
    raw = pathlib.Path(path).read_bytes()
    off, index = 0, 0
    while off < len(raw):
        if off + 12 > len(raw):
            raise WarmupError(f"{path}: truncated header at record {index}")
        (length,) = struct.unpack_from("<Q", raw, off)
        (len_crc,) = struct.unpack_from("<I", raw, off + 8)
        if masked_crc32c(raw[off:off + 8]) != len_crc:
            raise WarmupError(f"{path}: length checksum mismatch at record {index}")
        off += 12
        if off + length + 4 > len(raw):
            raise WarmupError(f"{path}: truncated payload at record {index}")
        data = raw[off:off + length]
        (data_crc,) = struct.unpack_from("<I", raw, off + length)
        if masked_crc32c(data) != data_crc:
            raise WarmupError(f"{path}: data checksum mismatch at record {index}")
        off += length + 4
        index += 1
        yield data


def frame_tfrecord(data: bytes) -> bytes:
    """One record's full framing as a single bytes object — the ONE
    framing producer (write_tfrecords + the request logger), and a single
    write() so a crash can truncate at most the final record."""
    header = struct.pack("<Q", len(data))
    return b"".join((
        header,
        struct.pack("<I", masked_crc32c(header)),
        data,
        struct.pack("<I", masked_crc32c(data)),
    ))


def write_tfrecords(path, payloads) -> None:
    """Write TFRecord framing (producer util for tests and export)."""
    with open(path, "wb") as f:
        for data in payloads:
            f.write(frame_tfrecord(data))


# ------------------------------------------------------------------- replay

def warmup_file_for(version_path) -> pathlib.Path | None:
    p = pathlib.Path(version_path) / WARMUP_DIRNAME / WARMUP_FILENAME
    return p if p.is_file() else None


def replay_warmup_file(path, servable: Servable, batcher) -> int:
    """Replay every PredictionLog in `path` against `servable` through the
    real service implementation + `batcher`. Returns the record count.

    The servable rides a THROWAWAY registry: at replay time the version is
    not yet publicly loaded (warmup precedes the registry flip, so live
    traffic never observes a cold version), and the record's own
    model_spec must not route anywhere else anyway.
    """
    from ..proto import serving_apis_pb2 as apis
    from .service import PredictionServiceImpl, ServiceError

    registry = ServableRegistry()
    registry.load(servable)
    impl = PredictionServiceImpl(registry, batcher)

    count = 0
    for index, payload in enumerate(read_tfrecords(path)):
        if index >= MAX_WARMUP_RECORDS:
            raise WarmupError(
                f"{path}: more than {MAX_WARMUP_RECORDS} warmup records "
                "(upstream cap; trim the file)"
            )
        log = apis.PredictionLog()
        try:
            log.ParseFromString(payload)
        except Exception as e:  # noqa: BLE001 — corrupt record, named index
            raise WarmupError(f"{path}: record {index} is not a PredictionLog: {e}") from e
        kind = log.WhichOneof("log_type")
        if kind is None:
            raise WarmupError(f"{path}: record {index} carries no log_type")
        sub = getattr(log, kind)
        request = sub.request

        # Target the version being loaded, whatever the producer recorded.
        # (MultiInferenceRequest carries specs per TASK, not at the top.)
        def retarget(spec) -> None:
            spec.name = servable.name
            spec.ClearField("version")
            spec.ClearField("version_label")

        try:
            if kind == "predict_log":
                retarget(request.model_spec)
                impl.predict(request)
            elif kind == "classify_log":
                retarget(request.model_spec)
                impl.classify(request)
            elif kind == "regress_log":
                retarget(request.model_spec)
                impl.regress(request)
            else:  # multi_inference_log
                for task in request.tasks:
                    retarget(task.model_spec)
                impl.multi_inference(request)
        except ServiceError as e:
            raise WarmupError(
                f"{path}: warmup record {index} ({kind}) failed: {e}"
            ) from e
        count += 1
    return count


def make_warmup_record(arrays: dict, model_name: str = "") -> bytes:
    """Serialize one predict-log warmup record (producer util)."""
    from .. import codec
    from ..proto import serving_apis_pb2 as apis

    log = apis.PredictionLog()
    req = log.predict_log.request
    req.model_spec.name = model_name
    for key, arr in arrays.items():
        codec.from_ndarray(arr, use_tensor_content=True, out=req.inputs[key])
    return log.SerializeToString()
