"""In-server multi-stage ranking cascade (ISSUE 19).

The reference system's whole client exists to shard a large candidate
set, score it with ONE expensive model, and sort/merge the results. The
cascade turns that into a server-side pipeline stage: a cheap first-stage
servable scores the full candidate batch, a jitted on-device prune keeps
the top-`survivor_k` rows (only the survivor (score, index) pairs plus
the wire-dtype stage-1 vector cross the D2H link — ops/transfer.py
cascade_prune_device), and the full DCN ranks only the survivors in the
smaller bucket rung. Stage-2 scores scatter back to their original
candidate positions, non-survivors keep their stage-1 scores, and the
response carries per-row provenance (`cascade_stage`: 1 = stage-1 score,
2 = stage-2 ranked) so callers can tell a ranked head from a pruned tail.

Composition is the point, not an afterthought:

- BOTH stages are ordinary DynamicBatcher submits of ordinary servables,
  so the score cache, row cache, overload lanes, deadline propagation,
  tracing, and recovery planes apply per stage for free. The stage-1
  prune submit salts its whole-request cache key (mode+k folded into the
  feature digest, cache/digest.py) so a prune result can never answer a
  full-vector request; the row plane keys on the model NAME, so stage-1
  rows can never poison stage-2 keys structurally.
- The first-stage model is a NORMAL servable published under its own
  model name (interop/export.py publish_version + train/checkpoint
  save_servable): the version watcher hot-swaps it, the lifecycle plane
  can canary it, and a mid-swap stale resolution simply falls back to a
  full stage-2 pass — no request fails because retrieval moved.
- Deadlines recompute between stages: stage 2 submits with the budget
  that REMAINS after stage 1, never the original allotment.
- Refused compositions (serving/server.py build_stack): `output_top_k`
  (its wire replaces the score vector the scatter needs) and [mesh]/
  [elastic] (the sharded run_fn has no prune entry). The fleet router
  forwards cascade traffic unchanged — the cascade is invisible at the
  RPC boundary except for the provenance output.

Per-request spans: `cascade.stage1` (submit + wait), `cascade.prune`
(host finalize: threshold filter + survivor gather), `cascade.stage2`.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..utils.tracing import request_trace

# Provenance output name (encoded into the response alongside the score
# tensor, the int8-wire sidecar precedent — not part of the signature).
STAGE_OUTPUT = "cascade_stage"
STAGE1 = 1  # row kept its stage-1 score (pruned before ranking)
STAGE2 = 2  # row was ranked by the full model


class CascadeStats:
    """Counter block behind /cascadez and dts_tpu_cascade_*. Lock-guarded:
    RPC handler threads from both transports bump it concurrently."""

    _FIELDS = (
        "requests", "fallbacks", "stage1_failures", "rows_requested",
        "rows_ranked", "pruned_rows", "survivor_rows",
        "zero_survivor_requests", "host_prunes",
    )

    def __init__(self):
        self._lock = threading.Lock()
        for f in self._FIELDS:
            setattr(self, f, 0)
        self.stage1_s = 0.0
        self.prune_s = 0.0
        self.stage2_s = 0.0
        # survivor-count histogram keyed by the bucket rung stage 2 ran
        # in — the capacity-planning view (which rungs the cascade feeds).
        self.survivor_buckets: dict[int, int] = {}


class CascadeOrchestrator:
    """Two-stage retrieval->rank pipeline above the DynamicBatcher.

    Consulted by PredictionServiceImpl per request (one attribute read
    when the plane is off). A request is eligible when its output filter
    pinned exactly the score output — the same gate that arms top-k
    compaction: the cascade's scatter needs a score VECTOR to fill, and
    mixed-stage values for any other output would be meaningless — and it
    carries at least `min_candidates` rows.
    """

    def __init__(
        self,
        registry,
        batcher,
        stage1_model: str = "stage1",
        survivor_k: int = 0,
        survivor_fraction: float = 0.25,
        score_threshold: float = 0.0,
        min_candidates: int = 8,
    ):
        self.registry = registry
        self.batcher = batcher
        self.stage1_model = stage1_model
        self.survivor_k = survivor_k
        self.survivor_fraction = survivor_fraction
        self.score_threshold = score_threshold
        self.min_candidates = min_candidates
        self.stats = CascadeStats()

    # ------------------------------------------------------- eligibility

    def eligible(self, servable, fetch_keys, n: int) -> bool:
        """Cheap per-request gate, called on the RPC handler thread."""
        return (
            n >= self.min_candidates
            and servable.name != self.stage1_model
            and fetch_keys is not None
            and len(fetch_keys) == 1
            and fetch_keys[0] == servable.model.score_output
            and self.plan_k(n) < n
        )

    def plan_k(self, n: int) -> int:
        """Survivor count for an n-candidate request: the fixed
        survivor_k when set, else the fraction of n (at least 1)."""
        if self.survivor_k > 0:
            return self.survivor_k
        return max(1, int(n * self.survivor_fraction))

    # ---------------------------------------------------------- pipeline

    def _stage1_servable(self):
        """Latest stage-1 version, or None (not yet published, or swapped
        out mid-rollout) — the caller falls back to a full stage-2 pass."""
        try:
            return self.registry.resolve(self.stage1_model, None)
        except Exception:  # noqa: BLE001 — NOT_FOUND during rollout
            return None

    def _finalize_prune(self, s1: dict, stage1, n: int, k: int):
        """Host tail of the prune: accept either the on-device prune
        result (survivor pairs + stage-1 vector) or a full score vector
        (the batcher's arming fallback — x64 model, custom run_fn), apply
        the optional score threshold, and return (survivor_indices,
        stage1_scores as a writable f32[n])."""
        if "survivor_indices" in s1:
            idx = np.asarray(s1["survivor_indices"])[:k]
            vals = np.asarray(s1["survivor_scores"], np.float32)[:k]
            full = np.array(s1["stage1_scores"], np.float32, copy=True)
        else:
            with self.stats._lock:
                self.stats.host_prunes += 1
            full = np.array(
                s1[stage1.model.score_output], np.float32, copy=True
            ).reshape(-1)
            # argpartition, then order the head by score descending so the
            # threshold filter below sees the same sorted view the device
            # top_k returns.
            idx = np.argpartition(-full, k - 1)[:k]
            idx = idx[np.argsort(-full[idx], kind="stable")]
            vals = full[idx]
        if self.score_threshold > 0.0:
            keep = vals >= self.score_threshold
            idx = idx[keep]
        return idx.astype(np.int64), full

    def _scatter(self, final: np.ndarray, idx, stage2_scores) -> dict:
        provenance = np.full(final.shape[0], STAGE1, np.int32)
        if len(idx):
            final[idx] = np.asarray(stage2_scores, np.float32).reshape(-1)
            provenance[idx] = STAGE2
        return provenance

    def _note(self, n: int, idx, bucket: int, t1: float, tp: float,
              t2: float) -> None:
        s = self.stats
        with s._lock:
            s.requests += 1
            s.rows_requested += n
            s.rows_ranked += len(idx)
            s.survivor_rows += len(idx)
            s.pruned_rows += n - len(idx)
            if len(idx) == 0:
                s.zero_survivor_requests += 1
            else:
                s.survivor_buckets[bucket] = (
                    s.survivor_buckets.get(bucket, 0) + 1
                )
            s.stage1_s += t1
            s.prune_s += tp
            s.stage2_s += t2

    def _note_fallback(self, n: int, stage1_failed: bool) -> None:
        s = self.stats
        with s._lock:
            s.requests += 1
            s.fallbacks += 1
            s.rows_requested += n
            s.rows_ranked += n
            if stage1_failed:
                s.stage1_failures += 1

    def _bucket_of(self, rows: int) -> int:
        from .batcher import bucket_for

        try:
            return bucket_for(rows, self.batcher.buckets)
        except Exception:  # noqa: BLE001 — accounting only
            return rows

    def run(self, impl, servable, arrays, fetch_keys, deadline_t,
            criticality) -> dict:
        """Synchronous cascade (thread-per-RPC transports). `impl` is the
        PredictionServiceImpl whose _run/_budget_left this rides — its
        error translation and degraded-marker forwarding apply per stage."""
        score_key = servable.model.score_output
        n = next(iter(arrays.values())).shape[0]
        k = self.plan_k(n)
        stage1 = self._stage1_servable()
        if stage1 is None:
            return self._full_fallback(
                impl, servable, arrays, fetch_keys, deadline_t,
                criticality, n, score_key, stage1_failed=False,
            )
        t0 = time.perf_counter()
        try:
            with request_trace.span("cascade.stage1"):
                s1 = impl._run(
                    stage1, arrays,
                    output_keys=(stage1.model.score_output,),
                    deadline_s=impl._budget_left(deadline_t),
                    criticality=criticality, prune_k=k,
                )
        except Exception:  # noqa: BLE001 — stage-1 must never fail the RPC
            # Mid-rollout unload, stage-1 shape mismatch, stage-1 device
            # failure: the contract is "retrieval trouble degrades to a
            # full ranking pass", so the request still succeeds.
            return self._full_fallback(
                impl, servable, arrays, fetch_keys, deadline_t,
                criticality, n, score_key, stage1_failed=True,
            )
        t1 = time.perf_counter()
        with request_trace.span("cascade.prune"):
            idx, final = self._finalize_prune(s1, stage1, n, k)
            surv = {key: v[idx] for key, v in arrays.items()} if len(idx) \
                else None
        tp = time.perf_counter()
        if surv is None:
            self._note(n, idx, 0, t1 - t0, tp - t1, 0.0)
            return {score_key: final, STAGE_OUTPUT: self._scatter(final, idx, [])}
        with request_trace.span("cascade.stage2"):
            out2 = impl._run(
                servable, surv, output_keys=fetch_keys,
                deadline_s=impl._budget_left(deadline_t),
                criticality=criticality,
            )
        t2 = time.perf_counter()
        provenance = self._scatter(final, idx, out2[score_key])
        self._note(n, idx, self._bucket_of(len(idx)), t1 - t0, tp - t1,
                   t2 - tp)
        return {score_key: final, STAGE_OUTPUT: provenance}

    async def run_async(self, impl, servable, arrays, fetch_keys,
                        deadline_t, criticality) -> dict:
        """run() for coroutine servers: identical semantics, stage waits
        are awaited instead of blocking the event-loop thread."""
        score_key = servable.model.score_output
        n = next(iter(arrays.values())).shape[0]
        k = self.plan_k(n)
        stage1 = self._stage1_servable()
        if stage1 is None:
            out = await impl._run_async(
                servable, arrays, output_keys=fetch_keys,
                deadline_s=impl._budget_left(deadline_t),
                criticality=criticality,
            )
            self._note_fallback(n, stage1_failed=False)
            return self._with_full_provenance(out, score_key, n)
        t0 = time.perf_counter()
        try:
            with request_trace.span("cascade.stage1"):
                s1 = await impl._run_async(
                    stage1, arrays,
                    output_keys=(stage1.model.score_output,),
                    deadline_s=impl._budget_left(deadline_t),
                    criticality=criticality, prune_k=k,
                )
        except Exception:  # noqa: BLE001 — stage-1 must never fail the RPC
            out = await impl._run_async(
                servable, arrays, output_keys=fetch_keys,
                deadline_s=impl._budget_left(deadline_t),
                criticality=criticality,
            )
            self._note_fallback(n, stage1_failed=True)
            return self._with_full_provenance(out, score_key, n)
        t1 = time.perf_counter()
        with request_trace.span("cascade.prune"):
            idx, final = self._finalize_prune(s1, stage1, n, k)
            surv = {key: v[idx] for key, v in arrays.items()} if len(idx) \
                else None
        tp = time.perf_counter()
        if surv is None:
            self._note(n, idx, 0, t1 - t0, tp - t1, 0.0)
            return {score_key: final, STAGE_OUTPUT: self._scatter(final, idx, [])}
        with request_trace.span("cascade.stage2"):
            out2 = await impl._run_async(
                servable, surv, output_keys=fetch_keys,
                deadline_s=impl._budget_left(deadline_t),
                criticality=criticality,
            )
        t2 = time.perf_counter()
        provenance = self._scatter(final, idx, out2[score_key])
        self._note(n, idx, self._bucket_of(len(idx)), t1 - t0, tp - t1,
                   t2 - tp)
        return {score_key: final, STAGE_OUTPUT: provenance}

    def _full_fallback(self, impl, servable, arrays, fetch_keys, deadline_t,
                       criticality, n, score_key, stage1_failed):
        """Full stage-2 pass (sync path): every row ranked, provenance
        all STAGE2 — the response a cascade-off server would have sent,
        plus honest provenance."""
        out = impl._run(
            servable, arrays, output_keys=fetch_keys,
            deadline_s=impl._budget_left(deadline_t),
            criticality=criticality,
        )
        self._note_fallback(n, stage1_failed)
        return self._with_full_provenance(out, score_key, n)

    @staticmethod
    def _with_full_provenance(out: dict, score_key: str, n: int) -> dict:
        out = dict(out)
        out[STAGE_OUTPUT] = np.full(n, STAGE2, np.int32)
        return out

    # ------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        """/cascadez + /monitoring?section=cascade + dts_tpu_cascade_*."""
        s = self.stats
        with s._lock:
            req = s.requests
            rows_req = s.rows_requested
            snap = {
                "stage1_model": self.stage1_model,
                "survivor_k": self.survivor_k,
                "survivor_fraction": self.survivor_fraction,
                "score_threshold": self.score_threshold,
                "min_candidates": self.min_candidates,
                "requests": req,
                "fallbacks": s.fallbacks,
                "stage1_failures": s.stage1_failures,
                "host_prunes": s.host_prunes,
                "rows_requested": rows_req,
                "rows_ranked": s.rows_ranked,
                "pruned_rows": s.pruned_rows,
                "survivor_rows": s.survivor_rows,
                "zero_survivor_requests": s.zero_survivor_requests,
                "survivor_fraction_observed": (
                    s.survivor_rows / rows_req if rows_req else 0.0
                ),
                "rank_fraction": (
                    s.rows_ranked / rows_req if rows_req else 0.0
                ),
                "stage1_seconds_total": s.stage1_s,
                "prune_seconds_total": s.prune_s,
                "stage2_seconds_total": s.stage2_s,
                "survivor_buckets": dict(
                    sorted(s.survivor_buckets.items())
                ),
            }
        return snap


def publish_stage1(base_dir: str, servable, kind: str) -> tuple[int, str]:
    """Publish a stage-1 servable as a normal versioned model: write a
    native checkpoint (train/checkpoint.save_servable) into the next
    numeric version slot via the atomic interop/export.publish_version
    rename, so a VersionWatcher on `base_dir` picks it up exactly like
    any other rollout (and the cascade's resolve sees the swap)."""
    from ..interop.export import publish_version
    from ..train.checkpoint import save_servable

    return publish_version(
        base_dir, lambda tmp: save_servable(tmp, servable, kind)
    )
