"""Serving stack: dynamic batcher + PredictionService semantics + gRPC frontend."""

from .batcher import (
    BatcherStats,
    BatchTooLargeError,
    DeviceWedgedError,
    DynamicBatcher,
    QueueOverloadError,
    bucket_for,
)
from .example_codec import ExampleDecodeError, decode_input, make_example
from .server import GrpcPredictionService, create_server, load_demo_servable, serve
from .service import PredictionServiceImpl, ServiceError
from .version_watcher import VersionWatcher, VersionWatcherConfig, scan_versions

__all__ = [
    "VersionWatcher",
    "VersionWatcherConfig",
    "scan_versions",
    "DynamicBatcher",
    "BatcherStats",
    "BatchTooLargeError",
    "QueueOverloadError",
    "DeviceWedgedError",
    "bucket_for",
    "decode_input",
    "make_example",
    "ExampleDecodeError",
    "PredictionServiceImpl",
    "ServiceError",
    "GrpcPredictionService",
    "create_server",
    "load_demo_servable",
    "serve",
]
