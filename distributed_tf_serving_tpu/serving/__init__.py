"""Serving stack: dynamic batcher + PredictionService semantics + gRPC frontend."""

from .batcher import (
    BatcherStats,
    BatchTooLargeError,
    DeviceWedgedError,
    DynamicBatcher,
    QueueOverloadError,
    bucket_for,
)
from .example_codec import ExampleDecodeError, decode_input, make_example
from .request_log import RequestLogger
from .server import (
    GrpcModelService,
    GrpcPredictionService,
    create_server,
    create_server_async,
    load_demo_servable,
    load_ssl_credentials,
    serve,
)
from .service import PredictionServiceImpl, ServiceError
from .version_watcher import VersionWatcher, VersionWatcherConfig, scan_versions
from .warmup import (
    WarmupError,
    read_tfrecords,
    replay_warmup_file,
    warmup_file_for,
    write_tfrecords,
)

__all__ = [
    "VersionWatcher",
    "VersionWatcherConfig",
    "scan_versions",
    "DynamicBatcher",
    "BatcherStats",
    "BatchTooLargeError",
    "QueueOverloadError",
    "DeviceWedgedError",
    "bucket_for",
    "decode_input",
    "make_example",
    "ExampleDecodeError",
    "PredictionServiceImpl",
    "ServiceError",
    "GrpcPredictionService",
    "GrpcModelService",
    "create_server",
    "create_server_async",
    "load_demo_servable",
    "load_ssl_credentials",
    "serve",
    "RequestLogger",
    "WarmupError",
    "read_tfrecords",
    "replay_warmup_file",
    "warmup_file_for",
    "write_tfrecords",
]
