"""Model-quality observability plane — what the model PREDICTS, per
(model, version), live (ISSUE 7).

Every other plane in the stack observes the machinery (latency spans,
occupancy, cache hits, admission); nothing observed the predictions
themselves, and ROADMAP item 5's canary/auto-rollback loop is gated on
exactly that signal: "live score-drift + windowed-AUC comparison between
versions in /monitoring". This module is that signal plane:

- **ScoreSketch**: a streaming fixed-bin histogram (mergeable — drift math
  and the reference snapshot are bin-wise) with moments, kept at two
  horizons: lifetime and a sliced rolling window (the WindowedLatency
  pattern: a ring of epoch-stamped sub-histograms, O(bins) record, no
  background thread).
- **Drift**: PSI and Jensen-Shannon divergence between binned score
  distributions — (a) the current window vs a PINNED reference snapshot
  (save/load as a JSON artifact: `artifacts/quality_reference.json`,
  pinned live via `POST /qualityz/snapshot`), and (b) the two live
  versions of a model whenever the version watcher has two serving
  concurrently (the `on_servable_change` hook mirrors the cache plane's
  invalidation wiring).
- **Label feedback**: `POST /labelz` joins (request/trace id | row digest
  from cache/digest.py — the ONE canonical row identity) + label + ts
  onto a bounded score reservoir, producing windowed AUC (the EXACT
  train/data.py::auc, not a reimplementation) and calibration (mean
  predicted vs observed rate, per predicted-probability decile).
- **Drift-linked exemplars**: when a drift check crosses the configured
  PSI threshold, the next N traced requests are annotated
  `quality.drift` — annotated spans are ALWAYS kept by the tail sampler
  (utils/tracing.TraceRecorder), so /tracez shows WHICH requests moved
  the distribution, not just that it moved.

Fed by ONE hook in the batcher completer (scores are already in host f32
memory post-readback; zero extra device work). Exclusions are structural:
warmup items are skipped explicitly, and cache hits / brownout
stale-serves never reach the completer at all — only freshly computed
scores are sketched. The request's criticality lane rides along as a
label. Off by default; when off the completer pays one attribute read.

jax-free by design: the monitor runs on completer/REST threads and in
tools with no device in sight.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

log = logging.getLogger("dts_tpu.quality")

# Lane label for observations that carried no criticality metadata — the
# overload plane's own default lane name, duplicated here so this module
# stays importable without the controller.
_DEFAULT_LANE = "default"
_KNOWN_LANES = ("critical", "default", "sheddable", "probe")


def _normalize_lane(lane) -> str:
    lane = str(lane).strip().lower() if lane else ""
    return lane if lane in _KNOWN_LANES else _DEFAULT_LANE


# --------------------------------------------------------------------------
# Drift math: PSI + Jensen-Shannon over binned distributions.


def _proportions(counts, eps: float) -> np.ndarray:
    """Bin proportions with additive smoothing — drift math must stay
    finite when a bin is empty on one side (the textbook PSI failure)."""
    c = np.asarray(counts, dtype=np.float64) + eps
    return c / c.sum()


def psi(expected_counts, actual_counts, eps: float = 1e-4) -> float:
    """Population Stability Index between two binned distributions
    (expected = the reference). Industry reading: < 0.1 stable, 0.1-0.25
    moderate shift, > 0.25 major shift; the plane's default alert
    threshold (0.2) sits inside the moderate band."""
    p = _proportions(expected_counts, eps)
    q = _proportions(actual_counts, eps)
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(p_counts, q_counts, eps: float = 1e-12) -> float:
    """Jensen-Shannon divergence (base 2: bounded [0, 1], symmetric) —
    the bounded companion to PSI, which is unbounded and jumpy on thin
    bins."""
    p = _proportions(p_counts, eps)
    q = _proportions(q_counts, eps)
    m = 0.5 * (p + q)

    def _kl(a, b):
        return float(np.sum(a * np.log2(a / b)))

    return 0.5 * _kl(p, m) + 0.5 * _kl(q, m)


def coarsen_counts(counts, target_bins: int) -> np.ndarray:
    """Merge adjacent histogram bins down to ~target_bins (bin-wise sums,
    so the result is still a valid distribution of the same data). PSI
    over many thin bins is dominated by sampling noise when one side's
    window is small — E[PSI] grows with occupied-bin count over sample
    size, and the empty-bin smoothing terms blow it up further — so
    DECISION consumers (the lifecycle rollback gate) compare coarsened
    views while the exposition keeps the fine bins."""
    counts = np.asarray(counts)
    k = max(2, min(int(target_bins), len(counts)))
    edges = np.linspace(0, len(counts), k + 1).astype(int)[:-1]
    return np.add.reduceat(counts, edges)


def histogram_percentile(
    counts, lo: float, hi: float, q: float
) -> float:
    """q in [0, 100] from a fixed-bin histogram over [lo, hi]; linear
    interpolation inside the winning bin."""
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    width = (hi - lo) / len(counts)
    target = q / 100.0 * total
    acc = 0.0
    for i, c in enumerate(counts):
        if acc + c >= target and c > 0:
            frac = (target - acc) / c
            return lo + width * (i + frac)
        acc += c
    return hi


# --------------------------------------------------------------------------
# Streaming sketch.


class ScoreSketch:
    """Streaming fixed-bin score histogram + moments, two horizons.

    Bins span [lo, hi] (CTR scores are sigmoid probabilities; out-of-range
    values clamp into the edge bins so nothing is silently dropped).
    Mergeable by construction: a distribution is its bin-count vector, so
    reference snapshots, version-pair drift, and cross-version merges are
    all element-wise adds. The rolling window is a ring of epoch-stamped
    slices (the utils/metrics.WindowedLatency pattern): record lands in
    the current slice, readout merges the slices still inside the window
    — O(bins) memory per slice, no background thread, injectable clock.
    """

    def __init__(
        self,
        bins: int = 50,
        lo: float = 0.0,
        hi: float = 1.0,
        window_s: float = 300.0,
        slices: int = 6,
        clock=time.monotonic,
    ):
        if hi <= lo:
            raise ValueError(f"sketch range [{lo}, {hi}] is empty")
        self.bins = max(2, int(bins))
        self.lo, self.hi = float(lo), float(hi)
        self.window_s = float(window_s)
        self.slices = max(2, int(slices))
        self.slice_s = self.window_s / self.slices
        self._clock = clock
        self._lock = threading.Lock()
        self._counts = np.zeros(self.bins, dtype=np.int64)
        self.count = 0
        self._sum = 0.0
        self._sum_sq = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._win_counts = np.zeros((self.slices, self.bins), dtype=np.int64)
        self._win_sums = [0.0] * self.slices
        self._win_sum_sqs = [0.0] * self.slices
        self._epochs = [-1] * self.slices

    def _bin_indices(self, scores: np.ndarray) -> np.ndarray:
        width = (self.hi - self.lo) / self.bins
        idx = np.floor((scores - self.lo) / width).astype(np.int64)
        return np.clip(idx, 0, self.bins - 1)

    def observe(self, scores) -> None:
        scores = np.asarray(scores, dtype=np.float64).ravel()
        if scores.size == 0:
            return
        binned = np.bincount(self._bin_indices(scores), minlength=self.bins)
        s, ss = float(scores.sum()), float(np.square(scores).sum())
        with self._lock:
            now = self._clock()
            epoch = int(now / self.slice_s)
            slot = epoch % self.slices
            if self._epochs[slot] != epoch:
                self._epochs[slot] = epoch
                self._win_counts[slot] = 0
                self._win_sums[slot] = 0.0
                self._win_sum_sqs[slot] = 0.0
            self._counts += binned
            self._win_counts[slot] += binned
            self._win_sums[slot] += s
            self._win_sum_sqs[slot] += ss
            self.count += scores.size
            self._sum += s
            self._sum_sq += ss
            self._min = min(self._min, float(scores.min()))
            self._max = max(self._max, float(scores.max()))

    def lifetime_counts(self) -> np.ndarray:
        with self._lock:
            return self._counts.copy()

    def window_counts(self) -> np.ndarray:
        """Merged bin counts of the slices still inside the window."""
        with self._lock:
            current = int(self._clock() / self.slice_s)
            out = np.zeros(self.bins, dtype=np.int64)
            for slot in range(self.slices):
                e = self._epochs[slot]
                if e >= 0 and current - e < self.slices:
                    out += self._win_counts[slot]
            return out

    def _window_moments(self) -> tuple[int, float, float]:
        with self._lock:
            current = int(self._clock() / self.slice_s)
            n, s, ss = 0, 0.0, 0.0
            for slot in range(self.slices):
                e = self._epochs[slot]
                if e >= 0 and current - e < self.slices:
                    n += int(self._win_counts[slot].sum())
                    s += self._win_sums[slot]
                    ss += self._win_sum_sqs[slot]
            return n, s, ss

    @staticmethod
    def _moment_stats(n: int, s: float, ss: float) -> dict:
        if n == 0:
            return {"count": 0, "mean": 0.0, "std": 0.0}
        mean = s / n
        var = max(ss / n - mean * mean, 0.0)
        return {"count": n, "mean": round(mean, 6), "std": round(math.sqrt(var), 6)}

    def snapshot(self) -> dict:
        counts = self.lifetime_counts()
        with self._lock:
            n, s, ss = self.count, self._sum, self._sum_sq
        win = self.window_counts()
        wn, wsum, wss = self._window_moments()
        pct = lambda c, q: round(  # noqa: E731
            histogram_percentile(c, self.lo, self.hi, q), 6
        )
        return {
            **self._moment_stats(n, s, ss),
            "min": round(self._min, 6) if n else 0.0,
            "max": round(self._max, 6) if n else 0.0,
            "p50": pct(counts, 50),
            "p90": pct(counts, 90),
            "p99": pct(counts, 99),
            "window": {
                "window_s": self.window_s,
                **self._moment_stats(wn, wsum, wss),
                "p50": pct(win, 50),
                "p99": pct(win, 99),
            },
        }


# --------------------------------------------------------------------------
# Label feedback: score reservoir + windowed (score, label) join.


# Re-exported from cache/digest.py — the ONE canonical row identity
# (shared with dedup and the score-cache key), so "this label belongs to
# that candidate" can never mean different bytes on the two sides.
from ..cache.digest import row_label_keys  # noqa: E402  (public API here)


class _LabelJoin:
    """Bounded score reservoir + the windowed (score, label) pair set.

    Reservoir entries are keyed by string id — a trace id (whole-request
    scores vector; `<trace_id>#<row>` addresses one candidate) or a row
    digest hex (one candidate's scalar score). LRU-bounded: feedback
    loops deliver labels minutes after the impression, so the reservoir
    holds the most recent keys and everything older joins as ORPHANED —
    visible, never silently dropped."""

    def __init__(
        self, max_keys: int = 8192, pair_window: int = 8192,
        window_s: float = 300.0, clock=time.monotonic,
    ):
        self.max_keys = max(16, int(max_keys))
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> (model, version, scores ndarray | float, t)
        self._reservoir: OrderedDict[str, tuple] = OrderedDict()
        self._pairs: deque[tuple] = deque(maxlen=max(16, int(pair_window)))
        self.joined = 0
        self.orphaned = 0
        self.late = 0
        # Label-feedback delay (seconds between the client-reported event
        # time `ts`, epoch wall clock, and ingest) — the loop-lag signal
        # a rollback gate must subtract before reading a windowed AUC.
        self.delay_count = 0
        self.delay_sum_s = 0.0
        self.delay_max_s = 0.0

    def put(self, key: str, model: str, version: int, scores, t: float) -> None:
        with self._lock:
            self._reservoir[key] = (model, version, scores, t)
            self._reservoir.move_to_end(key)
            while len(self._reservoir) > self.max_keys:
                self._reservoir.popitem(last=False)

    def reservoir_len(self) -> int:
        with self._lock:
            return len(self._reservoir)

    def ingest(self, key: str, label: float, ts: float | None = None) -> bool:
        """Join one label; True = joined, False = orphaned (no score under
        that key — evicted, never sampled, or a bad id). `<id>#<row>`
        addresses one row of a vector entry. `ts` (epoch seconds of the
        label EVENT, when the client reports one) feeds the feedback-
        delay telemetry; it is never used for window membership — the
        window runs on this process's monotonic clock, and trusting a
        remote wall clock there would let skew rewrite history."""
        if ts is not None:
            delay = time.time() - float(ts)
            if 0.0 <= delay < 7 * 86400.0:  # sane: not future, not ancient
                with self._lock:
                    self.delay_count += 1
                    self.delay_sum_s += delay
                    self.delay_max_s = max(self.delay_max_s, delay)
        base, _, row = key.partition("#")
        try:
            row_idx = int(row) if row else 0
        except ValueError:
            row_idx = -1
        with self._lock:
            entry = self._reservoir.get(base if row else key)
            if entry is None or row_idx < 0:
                self.orphaned += 1
                return False
            model, version, scores, t0 = entry
            if isinstance(scores, np.ndarray):
                if row_idx >= scores.size:
                    self.orphaned += 1
                    return False
                score = float(scores[row_idx])
            else:
                score = float(scores)
            now = self._clock()
            if now - t0 > self.window_s:
                # Joined, but the impression already aged out of the
                # rolling window — counted so a slow feedback loop is
                # visible as `late`, not mistaken for orphaning.
                self.late += 1
            self.joined += 1
            self._pairs.append((score, float(label), now, model, version))
            return True

    def window_pairs(
        self, model: str | None = None, version: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """In-window (score, label) pairs; model/version restrict to one
        series — the per-version windowed AUC the lifecycle plane's
        rollback gate compares between a stable and its canary."""
        with self._lock:
            cutoff = self._clock() - self.window_s
            live = [
                (s, l) for s, l, t, m, v in self._pairs
                if t >= cutoff
                and (model is None or m == model)
                and (version is None or int(v) == int(version))
            ]
        if not live:
            return np.empty(0), np.empty(0)
        arr = np.asarray(live, dtype=np.float64)
        return arr[:, 0], arr[:, 1]


def calibration_report(
    scores: np.ndarray, labels: np.ndarray, deciles: int = 10
) -> dict:
    """Mean predicted vs observed positive rate per predicted-probability
    decile, plus the count-weighted expected calibration error."""
    if scores.size == 0:
        return {"error": None, "deciles": []}
    edges = np.linspace(0.0, 1.0, deciles + 1)
    idx = np.clip(
        np.digitize(np.clip(scores, 0.0, 1.0), edges[1:-1]), 0, deciles - 1
    )
    out = []
    err = 0.0
    for d in range(deciles):
        mask = idx == d
        n = int(mask.sum())
        if n == 0:
            continue
        mean_pred = float(scores[mask].mean())
        observed = float(labels[mask].mean())
        err += n / scores.size * abs(mean_pred - observed)
        out.append({
            "decile": d,
            "count": n,
            "mean_predicted": round(mean_pred, 6),
            "observed_rate": round(observed, 6),
        })
    return {"error": round(err, 6), "deciles": out}


# --------------------------------------------------------------------------
# The monitor.


class QualityMonitor:
    """Per-(model, version) score-distribution plane + drift + label join.

    One `observe()` per completed (non-warmup) request from the batcher
    completer; everything else is read paths (/qualityz, /monitoring,
    Prometheus) or the label-feedback ingest. Thread-safe; the sketches
    carry their own locks so concurrent completers never serialize on the
    monitor lock for the histogram math."""

    # Bounded series space, the ServerMetrics precedent: client-supplied
    # model names must not grow sketches without limit.
    MAX_SERIES = 64

    def __init__(
        self,
        *,
        bins: int = 50,
        lo: float = 0.0,
        hi: float = 1.0,
        window_s: float = 300.0,
        slices: int = 6,
        drift_threshold_psi: float = 0.2,
        drift_check_interval_s: float = 5.0,
        exemplar_traces: int = 8,
        reservoir_keys: int = 8192,
        label_window: int = 8192,
        digest_rows_limit: int = 256,
        reference_file: str = "",
        min_drift_count: int = 50,
        clock=time.monotonic,
    ):
        self.bins, self.lo, self.hi = int(bins), float(lo), float(hi)
        self.window_s, self.slices = float(window_s), int(slices)
        self.drift_threshold_psi = float(drift_threshold_psi)
        self.drift_check_interval_s = float(drift_check_interval_s)
        self.exemplar_traces = int(exemplar_traces)
        self.digest_rows_limit = int(digest_rows_limit)
        self.reference_file = reference_file
        self.min_drift_count = int(min_drift_count)
        self._clock = clock
        self._lock = threading.Lock()
        self._sketches: dict[tuple[str, int], ScoreSketch] = {}
        self._lanes: dict[tuple[str, int], dict[str, int]] = {}
        # model -> {"counts": np.ndarray, "count": int, "pinned_at": float}
        self._reference: dict[str, dict] = {}
        self._labels = _LabelJoin(
            max_keys=reservoir_keys, pair_window=label_window,
            window_s=window_s, clock=clock,
        )
        self._last_drift_check = -math.inf
        self._last_drift: dict[str, dict] = {}
        self._exemplar_budget = 0
        self.exemplars_marked = 0
        self.drift_events = 0
        self.version_changes = 0
        self.observed_requests = 0
        self.series_overflow = 0
        if reference_file:
            try:
                self.load_reference(reference_file, missing_ok=True)
            except Exception:  # noqa: BLE001 — a corrupt artifact must
                log.exception(    # never fail serving startup
                    "could not load quality reference %s", reference_file
                )

    # ------------------------------------------------------------ ingestion

    def _sketch(self, model: str, version: int) -> ScoreSketch | None:
        key = (model, int(version))
        with self._lock:
            sk = self._sketches.get(key)
            if sk is None:
                if len(self._sketches) >= self.MAX_SERIES:
                    self.series_overflow += 1
                    return None
                sk = ScoreSketch(
                    bins=self.bins, lo=self.lo, hi=self.hi,
                    window_s=self.window_s, slices=self.slices,
                    clock=self._clock,
                )
                self._sketches[key] = sk
                self._lanes[key] = {}
            return sk

    def observe(
        self,
        model: str,
        version: int,
        scores,
        *,
        lane: str | None = None,
        span=None,
        arrays: dict[str, np.ndarray] | None = None,
        trace_id: str | None = None,
    ) -> None:
        """One completed request's freshly computed scores. Called from
        the batcher completer with warmup already excluded (cache hits and
        brownout stale-serves never reach the completer — structural
        exclusion). `span`/`trace_id` arm the exemplar + trace-id join
        paths when tracing is on; `arrays` (the request's decoded feature
        tensors) feeds the row-digest join for small requests."""
        scores = np.asarray(scores, dtype=np.float64).ravel()
        if scores.size == 0:
            return
        sketch = self._sketch(model, int(version))
        if sketch is None:
            return
        sketch.observe(scores)
        now = self._clock()
        lane = _normalize_lane(lane)
        with self._lock:
            self.observed_requests += 1
            lanes = self._lanes[(model, int(version))]
            lanes[lane] = lanes.get(lane, 0) + 1
        # Score reservoir for the label join — outside the lock (put()
        # locks internally); f32 copies so resolved futures can't alias.
        kept = scores.astype(np.float32)
        if trace_id:
            self._labels.put(trace_id, model, int(version), kept, now)
        if arrays is not None and scores.size <= self.digest_rows_limit:
            try:
                keys = row_label_keys(arrays)
            except Exception:  # noqa: BLE001 — odd dtypes must not
                keys = []      # poison the completer
            for i, key in enumerate(keys[: scores.size]):
                self._labels.put(key, model, int(version), float(kept[i]), now)
        # Drift tick: opportunistic, no background thread (the overload
        # controller's cadence pattern) — O(models x bins) at most once
        # per drift_check_interval_s.
        if now - self._last_drift_check >= self.drift_check_interval_s:
            self._drift_tick(now)
        # Drift-linked exemplar: while the budget is armed, annotate the
        # next traced requests — annotated spans are ALWAYS retained by
        # the tail sampler, so /tracez shows the requests that moved the
        # distribution.
        if span is not None and self._exemplar_budget > 0:
            with self._lock:
                if self._exemplar_budget <= 0:
                    return
                self._exemplar_budget -= 1
                self.exemplars_marked += 1
                worst = self._max_reference_psi()
            try:
                span.annotate(
                    "quality.drift", model=model, version=int(version),
                    psi=round(worst, 4) if worst is not None else None,
                )
            except Exception:  # noqa: BLE001 — a finished/odd span must
                pass           # never poison the completer

    def note_servable_change(self, model: str) -> None:
        """Version-watcher hook (load or retire) — the same wiring slot
        the cache plane's invalidation rides. Counts transitions; the
        version-pair drift itself reads from whatever versions have
        window data, so no bookkeeping beyond the sketches is needed."""
        with self._lock:
            self.version_changes += 1

    # ---------------------------------------------------------------- drift

    def _window_counts_locked(self, model: str) -> np.ndarray:
        """Merged window counts across every version of `model`. Caller
        must NOT hold the monitor lock for sketch reads (sketches lock
        themselves); this only reads the key list under the lock."""
        with self._lock:
            keys = [k for k in self._sketches if k[0] == model]
        out = np.zeros(self.bins, dtype=np.int64)
        for k in keys:
            out += self._sketches[k].window_counts()
        return out

    def _max_reference_psi(self) -> float | None:
        vals = [
            d["reference"]["psi"]
            for d in self._last_drift.values()
            if d.get("reference")
        ]
        return max(vals) if vals else None

    def _drift_tick(self, now: float) -> None:
        with self._lock:
            if now - self._last_drift_check < self.drift_check_interval_s:
                return  # another completer ticked while we raced here
            self._last_drift_check = now
            models = sorted({m for m, _v in self._sketches})
            reference = dict(self._reference)
        drift: dict[str, dict] = {}
        exceeded = False
        for model in models:
            entry: dict = {"reference": None, "version_pair": None}
            window = self._window_counts_locked(model)
            ref = reference.get(model)
            if ref is not None and window.sum() >= self.min_drift_count:
                entry["reference"] = {
                    "psi": round(psi(ref["counts"], window), 6),
                    "js": round(js_divergence(ref["counts"], window), 6),
                    "window_count": int(window.sum()),
                    "reference_count": int(ref["count"]),
                }
                if entry["reference"]["psi"] >= self.drift_threshold_psi:
                    exceeded = True
            entry["version_pair"] = self._version_pair_drift(model)
            if (
                entry["version_pair"] is not None
                and entry["version_pair"]["psi"] >= self.drift_threshold_psi
            ):
                exceeded = True
            drift[model] = entry
        with self._lock:
            was_armed = self._exemplar_budget > 0
            self._last_drift = drift
            if exceeded:
                if not was_armed:
                    self.drift_events += 1
                # Re-arm every tick while above threshold: a sustained
                # shift keeps producing exemplars at a bounded rate (N
                # per check interval), not one burst then silence.
                self._exemplar_budget = self.exemplar_traces
            elif not exceeded and was_armed:
                self._exemplar_budget = 0

    def _version_pair_drift(self, model: str) -> dict | None:
        """PSI/JS between the two live versions' windowed distributions —
        the canary-vs-stable comparison ROADMAP item 5 needs. 'Live' =
        has at least min_drift_count scores in the current window; with
        fewer than two live versions there is nothing to compare."""
        with self._lock:
            versions = sorted(v for m, v in self._sketches if m == model)
        live = []
        for v in versions:
            counts = self._sketches[(model, v)].window_counts()
            if counts.sum() >= self.min_drift_count:
                live.append((v, counts))
        if len(live) < 2:
            return None
        (v_old, c_old), (v_new, c_new) = live[-2], live[-1]
        return {
            "versions": [int(v_old), int(v_new)],
            "psi": round(psi(c_old, c_new), 6),
            "js": round(js_divergence(c_old, c_new), 6),
            "counts": [int(c_old.sum()), int(c_new.sum())],
        }

    # ------------------------------------------------ lifecycle read API

    def version_window_count(self, model: str, version: int) -> int:
        """Scores observed for one (model, version) inside the rolling
        window — the lifecycle controller's evidence floor before a
        canary may be judged (promote OR rollback)."""
        with self._lock:
            sk = self._sketches.get((model, int(version)))
        return int(sk.window_counts().sum()) if sk is not None else 0

    def pair_drift(
        self, model: str, v_old: int, v_new: int,
        min_count: int | None = None, decision_bins: int | None = None,
    ) -> dict | None:
        """PSI/JS between TWO NAMED versions' windowed distributions —
        the explicit (stable, canary) comparison the lifecycle rollback
        gate reads, as opposed to _version_pair_drift's 'two most active'
        heuristic the passive surfaces show. None until both sides hold
        at least `min_count` (default: this monitor's min_drift_count)
        windowed scores — drift over a handful of scores is noise.

        decision_bins coarsens both sides before the divergence math: a
        fresh canary's window is SMALL, and same-distribution PSI over
        50 thin bins at a few hundred samples measures 0.2-0.3 of pure
        sampling noise (measured; the empty-bin smoothing terms dominate)
        — within reach of a rollback threshold — while ~10 merged bins
        put the noise floor at ~0.03 with a genuine shift still reading
        >1. Gates should pass ~10; the passive surfaces keep the fine
        bins."""
        floor = self.min_drift_count if min_count is None else int(min_count)
        with self._lock:
            sk_old = self._sketches.get((model, int(v_old)))
            sk_new = self._sketches.get((model, int(v_new)))
        if sk_old is None or sk_new is None:
            return None
        c_old, c_new = sk_old.window_counts(), sk_new.window_counts()
        if c_old.sum() < floor or c_new.sum() < floor:
            return None
        if decision_bins:
            c_old = coarsen_counts(c_old, decision_bins)
            c_new = coarsen_counts(c_new, decision_bins)
        return {
            "versions": [int(v_old), int(v_new)],
            "psi": round(psi(c_old, c_new), 6),
            "js": round(js_divergence(c_old, c_new), 6),
            "counts": [int(c_old.sum()), int(c_new.sum())],
            "bins": int(len(c_old)),
        }

    def version_auc(
        self, model: str, version: int
    ) -> tuple[float | None, int]:
        """(windowed AUC, pair count) for ONE version's label-feedback
        joins — the exact train/data.py::auc, None when the window is
        empty or single-class. The lifecycle gate compares this between
        stable and canary before trusting an AUC delta."""
        scores, labels = self._labels.window_pairs(model, int(version))
        if scores.size == 0:
            return None, 0
        try:
            from ..train.data import auc as exact_auc  # jax-free module

            return round(float(exact_auc(labels, scores)), 6), int(scores.size)
        except ValueError:
            return None, int(scores.size)  # single-class window

    # ------------------------------------------------------------ reference

    def pin_reference(self, save: bool = True) -> dict:
        """Pin each model's CURRENT windowed distribution (merged across
        its versions; lifetime fallback when the window is empty) as the
        drift reference, and persist the artifact when a reference_file is
        configured. Returns {model: count_pinned, "path": ...}."""
        with self._lock:
            models = sorted({m for m, _v in self._sketches})
        pinned: dict = {}
        now = self._clock()
        for model in models:
            counts = self._window_counts_locked(model)
            if counts.sum() == 0:
                with self._lock:
                    keys = [k for k in self._sketches if k[0] == model]
                for k in keys:
                    counts += self._sketches[k].lifetime_counts()
            if counts.sum() == 0:
                continue
            with self._lock:
                self._reference[model] = {
                    "counts": counts.astype(np.int64),
                    "count": int(counts.sum()),
                    "pinned_at": now,
                }
            pinned[model] = int(counts.sum())
        path = None
        if save and self.reference_file:
            path = self.save_reference(self.reference_file)
        return {"models": pinned, "path": path}

    def save_reference(self, path: str) -> str:
        with self._lock:
            doc = {
                "bins": self.bins, "lo": self.lo, "hi": self.hi,
                "models": {
                    m: {
                        "counts": [int(c) for c in ref["counts"]],
                        "count": ref["count"],
                    }
                    for m, ref in self._reference.items()
                },
            }
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # atomic: a reader never sees a torn artifact
        return path

    def load_reference(self, path: str, missing_ok: bool = False) -> int:
        """Load a pinned-reference artifact; returns the number of model
        entries loaded. Entries whose bin geometry differs from this
        monitor's are skipped (logged) — comparing across geometries would
        produce confident nonsense."""
        if missing_ok and not os.path.exists(path):
            return 0
        with open(path) as f:
            doc = json.load(f)
        if (
            doc.get("bins") != self.bins
            or doc.get("lo") != self.lo
            or doc.get("hi") != self.hi
        ):
            log.warning(
                "quality reference %s has bin geometry (%s, %s, %s) != "
                "configured (%d, %s, %s); ignoring it",
                path, doc.get("bins"), doc.get("lo"), doc.get("hi"),
                self.bins, self.lo, self.hi,
            )
            return 0
        loaded = 0
        now = self._clock()
        with self._lock:
            for model, ref in (doc.get("models") or {}).items():
                counts = np.asarray(ref.get("counts", ()), dtype=np.int64)
                if counts.shape != (self.bins,) or counts.sum() <= 0:
                    continue
                self._reference[model] = {
                    "counts": counts,
                    "count": int(ref.get("count", counts.sum())),
                    "pinned_at": now,
                }
                loaded += 1
        return loaded

    # ------------------------------------------------------- label feedback

    def ingest_labels(self, items) -> dict:
        """POST /labelz body: items of {"id": str, "label": 0|1,
        "ts": optional epoch seconds of the label event}. Returns
        joined/orphaned counts for THIS call.

        Labels are BINARY: the windowed AUC ranks against exact class
        membership (train/data.py::auc), so a fractional "label" would
        silently produce garbage — refused up front instead. The whole
        batch is validated BEFORE any item is applied: a malformed item
        mid-list must not leave a joined prefix behind a 400 (the
        client's retry would double-count those pairs)."""
        validated = []
        for item in items:
            if not isinstance(item, dict) or "id" not in item or "label" not in item:
                raise ValueError(
                    'each label item needs "id" and "label" fields'
                )
            label = float(item["label"])
            if label not in (0.0, 1.0):
                raise ValueError(f"label must be 0 or 1, got {label}")
            ts = item.get("ts")
            if ts is not None:
                ts = float(ts)
            validated.append((str(item["id"]), label, ts))
        joined = orphaned = 0
        for key, label, ts in validated:
            if self._labels.ingest(key, label, ts):
                joined += 1
            else:
                orphaned += 1
        return {"joined": joined, "orphaned": orphaned}

    def _label_block(self) -> dict:
        scores, labels = self._labels.window_pairs()
        auc_val = None
        if scores.size:
            try:
                from ..train.data import auc as exact_auc  # jax-free module

                auc_val = round(float(exact_auc(labels, scores)), 6)
            except ValueError:
                auc_val = None  # single-class window: AUC undefined
        lj = self._labels
        return {
            "joined": lj.joined,
            "orphaned": lj.orphaned,
            "late": lj.late,
            "window_pairs": int(scores.size),
            "reservoir_keys": lj.reservoir_len(),
            "auc": auc_val,
            "calibration": calibration_report(scores, labels),
            # Client-reported event-time lag (ts -> ingest): how stale
            # the feedback loop itself runs.
            "feedback_delay": {
                "count": lj.delay_count,
                "mean_s": round(
                    lj.delay_sum_s / lj.delay_count, 3
                ) if lj.delay_count else None,
                "max_s": round(lj.delay_max_s, 3),
            },
        }

    # --------------------------------------------------------------- export

    def snapshot(self, model: str | None = None, version: int | None = None) -> dict:
        """The /qualityz body (and the `quality` /monitoring block).
        model=/version= restrict the per-series detail; drift, labels, and
        the counters are plane-wide either way."""
        with self._lock:
            keys = sorted(self._sketches)
            drift = {
                m: {
                    k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in d.items()
                }
                for m, d in self._last_drift.items()
            }
            reference = {
                m: {"count": ref["count"], "pinned_at": round(ref["pinned_at"], 3)}
                for m, ref in self._reference.items()
            }
            counters = {
                "observed_requests": self.observed_requests,
                "version_changes": self.version_changes,
                "series_overflow": self.series_overflow,
            }
            exemplars = {
                "budget": self._exemplar_budget,
                "marked": self.exemplars_marked,
                "drift_events": self.drift_events,
            }
        models: dict = {}
        for m, v in keys:
            if model is not None and m != model:
                continue
            if version is not None and v != int(version):
                continue
            blk = models.setdefault(m, {"versions": {}})
            sk = self._sketches[(m, v)]
            snap = sk.snapshot()
            snap["lanes"] = dict(self._lanes.get((m, v), {}))
            # Raw lifetime bin counts ride the snapshot so exporters (the
            # Prometheus histogram family) and offline drift tooling can
            # work from the JSON alone, no monitor object in hand.
            snap["histogram"] = {
                "lo": self.lo, "hi": self.hi,
                "counts": [int(c) for c in sk.lifetime_counts()],
            }
            blk["versions"][str(v)] = snap
        for m, blk in models.items():
            d = drift.get(m, {"reference": None, "version_pair": None})
            ref_psi = (d.get("reference") or {}).get("psi")
            pair_psi = (d.get("version_pair") or {}).get("psi")
            blk["drift"] = {
                **d,
                "threshold_psi": self.drift_threshold_psi,
                "exceeded": any(
                    p is not None and p >= self.drift_threshold_psi
                    for p in (ref_psi, pair_psi)
                ),
            }
            blk["reference_pinned"] = m in reference
        return {
            "enabled": True,
            "config": {
                "bins": self.bins, "lo": self.lo, "hi": self.hi,
                "window_s": self.window_s,
                "drift_threshold_psi": self.drift_threshold_psi,
                "drift_check_interval_s": self.drift_check_interval_s,
                "exemplar_traces": self.exemplar_traces,
                "reference_file": self.reference_file,
            },
            **counters,
            "exemplars": exemplars,
            "reference": reference,
            "labels": self._label_block(),
            "models": models,
        }
