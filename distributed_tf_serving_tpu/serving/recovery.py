"""Device-failure recovery plane — executor quarantine, in-flight batch
replay, poisoned-input bisection (ISSUE 11).

PR 9's k-deep continuous batching raised the blast radius of a device
fault: one wedged jit call or readback strands up to `inflight_window`
batches plus everything queued behind them, and before this plane the
only exits were DeviceWedgedError failing every affected request or
killing the process. TF-Serving treats servable isolation and recovery as
a first-class serving concern; at the fleet scale of "Scaling TensorFlow
to 300 million predictions per second" a replica that self-heals in
seconds instead of paging a human is the difference between a blip and an
incident. This module turns device failure from request death into a
bounded, observable recovery cycle:

    SERVING -> QUARANTINED -> REINIT -> REPLAY -> SERVING

- **Quarantine decision.** A watchdog escalates the batcher's EXISTING
  wedge clock (`DynamicBatcher.wedge_age` — the same
  dispatching/in-flight timestamps the circuit breaker reads, at a
  usually much lower threshold) and the completer-side failure hooks into
  a trigger: a device-fatal batch failure (`take_group` — injected
  device_lost/executor_abort faults, XLA DEVICE_LOST-shaped runtime
  errors), a wedged device (watchdog), or a dead batcher thread
  (`note_thread_death`). Transient non-device errors never trigger it —
  they keep today's fail-the-group semantics.

- **QUARANTINED.** grpc.health.v1 flips to NOT_SERVING (the health
  servicer reads `not_serving()`), new submits are refused fast with
  DeviceQuarantinedError (UNAVAILABLE — fan-out clients reroute via the
  PR-2 scoreboard), the lifecycle plane's canary ticks pause (a rollout
  must not judge a canary against a dying device), and EVERY accepted-
  but-unanswered work item is captured out of the batcher — queued,
  staged, dispatching, and in-flight (the capture clears the wedge
  bookkeeping; the stranded threads no-op or lose the set-result race by
  construction).

- **REINIT.** The jitted entries and content-addressed device input
  cache are torn down and rebuilt in-process (fresh executables against a
  fresh backend state; `jax.clear_caches()`, optionally the backend
  itself), wedged worker pools are replaced (a thread stuck in native
  code cannot be preempted — the pool around it can), a dead batching
  thread is revived, and the bucket ladder re-warms THROUGH the queue —
  warmup exempt from occupancy and the wedge clock, as today.

- **REPLAY.** Captured items re-enter the queue FRONT with their
  original host arrays (the padded device-side buffers of a failed batch
  are never recycled into the _HostBufferRing — they leak to GC, the
  ring's recycle-contract extension) and a per-item replay budget. A
  batch that deterministically kills the executor again is BISECTED: its
  member requests split into halves carrying distinct `bisect_key`s (the
  coalescer only merges equal keys), each half replays as its own batch,
  and the half that keeps killing splits again until a SINGLE request is
  isolated — it alone fails with PoisonedInputError (INVALID_ARGUMENT,
  the distinct do-not-retry status) while its batchmates are
  re-dispatched and succeed.

Off by default ([recovery] enabled=false / --recovery); when off the
batcher pays one attribute read per hook — the tracing/cache/overload
precedent — and behavior is bit-identical to the pre-plane stack.
Surfaces: GET /recoveryz, a `recovery` block in /monitoring, and
dts_tpu_recovery_* Prometheus series.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .batcher import (
    DeviceWedgedError,
    PoisonedInputError,
    poison_fault_key,  # noqa: F401 — re-exported for tests/soaks
)

log = logging.getLogger("dts_tpu.recovery")

# States (string values are the wire/JSON encoding, lowercase for labels).
SERVING = "serving"
QUARANTINED = "quarantined"
REINIT = "reinit"
REPLAY = "replay"
STATES = (SERVING, QUARANTINED, REINIT, REPLAY)

# Fault-injector sites classified device-fatal, and the error-message
# markers a real runtime's device death carries (XlaRuntimeError text —
# kept narrow: an ordinary INVALID_ARGUMENT trace error must never read
# as a dead device).
_FATAL_SITES = ("device_lost", "executor_abort")
_FATAL_MARKERS = (
    "DEVICE_LOST", "device lost", "Device lost", "DATA_LOSS",
    "executor aborted",
)


def device_fatal(exc: BaseException) -> bool:
    """True when `exc` means the device executor is gone (quarantine +
    replay), False for everything else (today's fail-the-group path)."""
    from .. import faults as faults_mod

    if isinstance(exc, faults_mod.InjectedFaultError):
        return exc.site in _FATAL_SITES
    if getattr(exc, "integrity_corrupt", False):
        # Integrity-plane verdicts (shadow mismatch, screen-trip
        # escalation) opt in explicitly: the executor's outputs can no
        # longer be trusted, so the same quarantine->reinit->replay
        # cycle applies even though the device did not report dead.
        return True
    # Marker match only — deliberately narrow: a deterministic per-shape
    # XlaRuntimeError("INTERNAL: ...") compile/runtime bug is NOT a dead
    # device, and classifying it fatal would loop quarantine cycles (and
    # eventually convict requests as poisoned) over an error today's
    # fail-the-group path reports in one RPC.
    msg = str(exc)
    return any(m in msg for m in _FATAL_MARKERS)


class RecoveryController:
    """The quarantine -> reinit -> replay state machine over one batcher.

    Collaborators are injected — `batcher` (capture/requeue/reinit
    surface; the controller attaches itself as `batcher.recovery`),
    `registry` (which servables to re-warm after REINIT; None skips the
    re-warm), `impl` (late-bound lifecycle access: the canary ticks pause
    while quarantined) — so the machine is testable with a fake clock, a
    fake batcher, and no threads (`run_cycle()` is the whole cycle;
    `check()` is one watchdog pass). `start()` adds the optional
    background watchdog."""

    def __init__(
        self,
        config,
        batcher,
        registry=None,
        impl=None,
        lifecycle=None,
        clock=time.monotonic,
    ):
        self.config = config
        self.batcher = batcher
        self.registry = registry
        self.impl = impl
        self.lifecycle = lifecycle
        self._clock = clock
        self._lock = threading.Lock()
        # One cycle at a time: a failure arriving mid-cycle lands in
        # _pending and the active cycle's round loop absorbs it.
        self._cycle_mutex = threading.Lock()
        self._state = SERVING
        self._state_since = clock()
        # Replay units: lists of _WorkItems that must re-dispatch
        # together (a bisection half shares one unit + bisect_key).
        self._pending: list[list] = []
        self._pending_ids: set[int] = set()
        self._bisect_seq = 0
        self._trigger: str | None = None
        # Spawn one-shot cycle threads on demand when no watchdog runs.
        # Tests that drive run_cycle() themselves set this False.
        self.auto_cycle = True
        # Counters (all monotonic; Prometheus reads them off snapshot()).
        self.quarantines = 0
        self.reinits = 0
        self.cycles_completed = 0
        self.device_failures = 0
        self.replayed_items = 0
        self.replay_budget_exhausted = 0
        self.poisoned_requests = 0
        self.bisections = 0
        self.watchdog_wedge_trips = 0
        self.thread_deaths = 0
        self._last_cycle: dict | None = None
        self._events: deque[dict] = deque(
            maxlen=max(int(getattr(config, "history_events", 64)), 8)
        )
        # Per-cycle MTTR history ring (ISSUE 12 satellite): one record
        # per completed cycle — the longitudinal evidence /recoveryz
        # serves next to the instantaneous last_cycle ("is recovery
        # getting slower as this replica degrades?").
        self._mttr_ring: deque[dict] = deque(
            maxlen=max(int(getattr(config, "history_events", 64)), 8)
        )
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._worker: threading.Thread | None = None
        batcher.recovery = self

    # ---------------------------------------------------------- fast reads
    # Lock-free single-attribute reads: these run inside batcher.submit
    # (under the batcher's condition variable) and inside future
    # done-callbacks — taking self._lock there could deadlock against a
    # cycle thread resolving futures.

    def state(self) -> str:
        return self._state

    def refusing(self) -> bool:
        """New (non-warmup) submits are refused while the executor is
        down or rebuilding; REPLAY accepts again — replayed items are
        merely queue-first."""
        return self._state in (QUARANTINED, REINIT)

    def not_serving(self) -> bool:
        """grpc.health.v1 reports NOT_SERVING through the whole cycle:
        load balancers route back only once replay has drained."""
        return self._state != SERVING

    def cycle_active(self) -> bool:
        """True while a cycle is running OR work is captured/requested —
        batcher.drain() observes this so a drain mid-REINIT neither
        returns a false 'drained' nor waits past its own bound."""
        return (
            self._state != SERVING
            or bool(self._pending)
            or self._trigger is not None
        )

    # ------------------------------------------------------------ triggers

    def take_group(self, group: list, exc: BaseException) -> bool:
        """Batcher failure hook: called from _run_stage/_complete when a
        batch fails. True = this failure is device-fatal and the
        controller now owns the group's outcome (futures resolve from
        replay, or with the poisoned/budget-exhausted status); False =
        not a device failure, fail the group exactly as before."""
        if self._stop_evt.is_set():
            # A stopped controller (drain in progress) must not capture
            # work nobody will replay.
            return False
        if not device_fatal(exc):
            return False
        self.device_failures += 1
        fails: list[tuple] = []
        for it in group:
            if it.warmup and not it.future.done():
                # Warmup is re-run wholesale by REINIT; replaying the
                # item too would double-compile for nothing.
                fails.append((it, exc))
        self._apply_fails(fails)
        self._absorb([it for it in group if not it.warmup], exc)
        trigger = (
            "output_corrupt"
            if getattr(exc, "integrity_corrupt", False)
            else "device_fatal"
        )
        self._request_cycle(trigger)
        return True

    def note_thread_death(self, err: BaseException) -> bool:
        """Batcher thread-death hook: revive + replay via a cycle. False
        when this controller is stopped (drain in progress) — the caller
        must then fail queued waiters fast itself, or they would hang
        between a dead thread and a cycle that will never run."""
        if self._stop_evt.is_set():
            return False
        self.thread_deaths += 1
        self._request_cycle("thread_death")
        return True

    def check(self) -> str:
        """One watchdog pass: escalate the batcher's wedge clock into a
        quarantine decision, then run any requested cycle. Returns the
        state afterward. The background watchdog calls this on its
        interval; tests drive it directly."""
        if self._stop_evt.is_set():
            return self._state
        with self._lock:
            trig = self._trigger
        if trig is None and self._state == SERVING:
            age = self._safe(self.batcher.wedge_age, 0.0) or 0.0
            threshold = max(self.config.wedge_quarantine_s, 0.1)
            if age >= threshold:
                self.watchdog_wedge_trips += 1
                with self._lock:
                    self._trigger = trig = "wedge"
        if trig is not None:
            self.run_cycle(trig)
        return self._state

    def _request_cycle(self, trigger: str) -> None:
        with self._lock:
            if self._trigger is None:
                self._trigger = trigger
        if self._stop_evt.is_set():
            return
        if self._worker is not None and self._worker.is_alive():
            self._wake.set()
        elif self.auto_cycle:
            threading.Thread(
                target=self.run_cycle, args=(trigger,),
                name="recovery-cycle", daemon=True,
            ).start()

    # --------------------------------------------------- failure absorption

    def _absorb(self, group: list, exc: BaseException | None) -> None:
        """Classify one failed/abandoned batch's live items into replay
        units: kill accounting, the poison verdict (a single-request
        batch that keeps killing), the per-item replay budget, and the
        bisection split. Future resolution happens OUTSIDE the lock —
        done-callbacks (cache single-flight) re-enter the batcher."""
        cfg = self.config
        fails: list[tuple] = []
        with self._lock:
            live = [
                it for it in group
                if not it.future.done() and id(it) not in self._pending_ids
            ]
            if not live:
                return
            for it in live:
                it.device_kills += 1
            kills = max(it.device_kills for it in live)
            # The poison VERDICT (INVALID_ARGUMENT — "do not retry these
            # bytes anywhere") demands an actual device-kill ERROR on the
            # final solo batch. Wedge-derived kills (exc None) still
            # drive bisection and burn replay budget, but a persistently
            # wedging DEVICE must fail its solo captives with the
            # retryable wedge error (budget exhaustion below), never
            # convict innocent requests a healthy replica would serve.
            if (
                len(live) == 1
                and exc is not None
                and kills >= max(cfg.poison_kills, 1)
            ):
                it = live[0]
                self.poisoned_requests += 1
                err = PoisonedInputError(
                    "poisoned input isolated by recovery bisection: this "
                    "request's batch deterministically killed the device "
                    f"executor {it.device_kills}x (last failure: "
                    f"{type(exc).__name__ if exc is not None else 'wedge'}); "
                    "failing it alone — do not retry these bytes"
                )
                if exc is not None:
                    err.__cause__ = exc
                fails.append((it, err))
            else:
                keep = []
                for it in live:
                    if it.replays >= max(cfg.replay_budget, 1):
                        self.replay_budget_exhausted += 1
                        err = exc if exc is not None else DeviceWedgedError(
                            "batch abandoned by recovery quarantine and "
                            "replay budget exhausted"
                        )
                        fails.append((it, err))
                    else:
                        keep.append(it)
                if keep:
                    if len(keep) > 1 and kills >= max(cfg.bisect_after_kills, 1):
                        # Deterministic killer: split into halves, each a
                        # separate replay unit the coalescer keeps apart.
                        self.bisections += 1
                        mid = (len(keep) + 1) // 2
                        for half in (keep[:mid], keep[mid:]):
                            if half:
                                self._bisect_seq += 1
                                for it in half:
                                    it.bisect_key = self._bisect_seq
                                self._stash_locked(half)
                    else:
                        self._stash_locked(keep)
        self._apply_fails(fails)

    def _stash_locked(self, unit: list) -> None:
        self._pending.append(unit)
        self._pending_ids.update(id(it) for it in unit)

    def _drain_pending(self) -> list[list]:
        with self._lock:
            units, self._pending = self._pending, []
            self._pending_ids.clear()
        return units

    @staticmethod
    def _apply_fails(fails: list) -> None:
        from concurrent.futures import InvalidStateError

        for it, err in fails:
            try:
                if not it.future.done():
                    it.future.set_exception(err)
            except InvalidStateError:
                pass

    # ------------------------------------------------------------ the cycle

    def run_cycle(self, trigger: str = "manual") -> bool:
        """One full QUARANTINED -> REINIT -> REPLAY -> SERVING pass,
        looping reinit+replay rounds until the replay drains clean (a
        replayed batch that kills the executor again re-enters _pending
        through take_group and forces another round — this is how the
        bisection converges inside ONE cycle). False when another cycle
        already holds the mutex (it will absorb the pending work)."""
        if not self._cycle_mutex.acquire(blocking=False):
            return False
        try:
            t0 = self._clock()
            with self._lock:
                trig = self._trigger or trigger
                self._trigger = None
            self.quarantines += 1
            self._enter(QUARANTINED, trigger=trig)
            lc = self._lifecycle()
            if lc is not None:
                # Canary ticks pause: a rollout must not judge (or
                # promote) a canary against a dying device.
                self._safe(lambda: lc.pause())
            queued, inflight = self._safe(
                self.batcher.capture_for_recovery, ([], [])
            ) or ([], [])
            if queued:
                with self._lock:
                    self._stash_locked(list(queued))
            for group in inflight:
                # These groups were IN a device call when the device was
                # declared gone — the wedge is their kill evidence, so
                # the bisection converges on wedge-shaped poison too.
                self._absorb(group, None)
            if trig in ("wedge", "thread_death"):
                # A thread stuck in native device code cannot be
                # preempted in-process; the pools around it can.
                self._safe(self.batcher.replace_workers_for_recovery)
            rounds = 0
            replayed_this_cycle = 0
            failed_this_cycle = 0
            poisoned_before = self.poisoned_requests
            while not self._stop_evt.is_set():
                rounds += 1
                self._enter(REINIT, round=rounds)
                self.reinits += 1
                self._reinit_executors()
                if getattr(self.config, "reinit_warmup", True):
                    self._rewarm()
                # Atomic drain + trigger clear: the trigger may only be
                # consumed while _pending is observably empty in the SAME
                # lock hold — a take_group stashing work between a drain
                # and a separate trigger-clear would otherwise be erased
                # with its items stranded in _pending and no cycle ever
                # scheduled for them.
                with self._lock:
                    units, self._pending = self._pending, []
                    self._pending_ids.clear()
                    if not units:
                        # This round's reinit also covers any kill that
                        # raced the previous round's drain but left
                        # nothing to replay (a poison verdict's final
                        # solo kill): the trigger it set is satisfied
                        # here, not by a whole extra quarantine cycle
                        # after this one ends.
                        self._trigger = None
                if not units:
                    break
                self._enter(REPLAY, round=rounds, units=len(units))
                futs = []
                for unit in units:
                    for it in unit:
                        it.replays += 1
                    self.replayed_items += len(unit)
                    replayed_this_cycle += len(unit)
                    self._safe(
                        lambda u=unit: self.batcher.requeue_for_replay(u)
                    )
                    futs.extend(it.future for it in unit)
                self._wait_replay(futs)
                with self._lock:
                    still_pending = bool(self._pending)
                    retriggered = self._trigger is not None
                    if not still_pending:
                        self._trigger = None
                if not still_pending:
                    if retriggered:
                        # A kill landed during this replay but resolved
                        # every item it touched (poison verdict): the
                        # executor still died AFTER the last reinit, so
                        # run one more reinit round before declaring the
                        # cycle done.
                        continue
                    break
                if rounds >= max(int(self.config.max_cycle_rounds), 1):
                    err = DeviceWedgedError(
                        f"recovery gave up after {rounds} reinit/replay "
                        "rounds; the device keeps failing"
                    )
                    for unit in self._drain_pending():
                        failed_this_cycle += len(unit)
                        self._apply_fails([(it, err) for it in unit])
                    break
            if lc is not None:
                self._safe(lambda: lc.resume())
            duration = self._clock() - t0
            with self._lock:
                self.cycles_completed += 1
                self._last_cycle = {
                    "trigger": trig,
                    "rounds": rounds,
                    "duration_s": round(duration, 4),
                    "replayed_items": replayed_this_cycle,
                    "poisoned": self.poisoned_requests - poisoned_before,
                    "gave_up_items": failed_this_cycle,
                }
                self._mttr_ring.append({
                    "t": round(t0, 3),
                    "trigger": trig,
                    "mttr_s": round(duration, 4),
                    "rounds": rounds,
                    "replayed_items": replayed_this_cycle,
                })
            self._enter(SERVING, trigger=trig,
                        duration_s=round(duration, 4))
            return True
        finally:
            self._cycle_mutex.release()

    def _reinit_executors(self) -> None:
        """Tear down and rebuild the device-execution state in-process:
        fresh jitted entries, a cleared content-addressed input cache
        (its device arrays reference the dead backend), cleared jax
        compilation caches, a revived batching thread if one died, and —
        config-gated, heavyweight — the backend itself."""
        b = self.batcher
        try:
            with b._jit_lock:
                b._jitted.clear()
        except Exception:  # noqa: BLE001 — a fake batcher may lack these
            pass
        # [recovery]×[mesh] compose (ISSUE 15): a custom run_fn that owns
        # device state (the ShardedExecutor's placed params + sharded
        # executables, or the elastic executor's whole ladder) is part of
        # the executor unit this plane recovers — clear it like the
        # single-chip entries above. Executors without the hook (tests'
        # plain callables) are untouched.
        run_fn = getattr(b, "_run_fn", None)
        clear_run_fn = getattr(run_fn, "clear_for_recovery", None)
        if clear_run_fn is not None:
            self._safe(clear_run_fn)
        cache = getattr(b, "input_cache", None)
        if cache is not None:
            self._safe(cache.clear)
        self._safe(b.revive_batching_thread)
        try:
            import jax

            jax.clear_caches()
            if getattr(self.config, "reinit_clear_backend", False):
                # Deprecated-but-present escape hatch: a genuinely lost
                # TPU needs the runtime client rebuilt, not just fresh
                # executables. Never the default — it is process-global.
                clear = getattr(jax, "clear_backends", None)
                if clear is not None:
                    clear()
        except Exception:  # noqa: BLE001 — cache clearing is best-effort
            log.exception("recovery: jax cache clear failed")

    def _rewarm(self) -> None:
        """Re-warm every registered servable's bucket ladder THROUGH the
        queue (compiles on the batching thread; _warmup=True keeps the
        wedge clock, occupancy ledger, and the quarantine gate out of
        it). Bounded; failures log and never wedge the cycle."""
        from concurrent.futures import wait as fut_wait

        reg = self.registry
        b = self.batcher
        if reg is None:
            return
        try:
            names = sorted(reg.models() or {})
        except Exception:  # noqa: BLE001 — registry quirks never wedge
            return
        futs = []
        for name in names:
            try:
                sv = reg.resolve(name)
            except Exception:  # noqa: BLE001 — vanished mid-cycle
                continue
            for bucket in b.buckets:
                try:
                    futs.append(b.submit(
                        sv, b.warmup_arrays(sv, bucket), _warmup=True
                    ))
                except Exception:  # noqa: BLE001 — keep warming the rest
                    log.exception("recovery re-warm submit failed (%s/%d)",
                                  name, bucket)
        if futs:
            fut_wait(futs, timeout=max(
                getattr(self.config, "rewarm_timeout_s", 120.0), 1.0
            ))
        run_fn = getattr(b, "_run_fn", None)
        if getattr(run_fn, "elastic", False):
            # Elastic mesh (ISSUE 15): the queue re-warm above compiled
            # only the CURRENT split's executables. Re-warm the whole
            # ladder directly (batcher.warmup routes elastic run_fns
            # through warmup_call, every split) so a post-recovery
            # switch keeps the never-compiles-on-the-serving-path
            # contract — a rung compiling under the wedge clock would
            # trip a spurious re-quarantine.
            for name in names:
                try:
                    sv = reg.resolve(name)
                except Exception:  # noqa: BLE001 — vanished mid-cycle
                    continue
                try:
                    b.warmup(sv)
                except Exception:  # noqa: BLE001 — keep warming the rest
                    log.exception(
                        "recovery elastic ladder re-warm failed (%s)", name
                    )

    def _wait_replay(self, futs: list) -> None:
        """Bounded wait for the replayed futures: ends early when a
        replayed batch fails device-fatally again (pending refills — the
        round loop reinits and replays the split immediately) or when a
        drain is stopping the controller. Wall-clock bounded regardless
        of the injected state-machine clock."""
        from concurrent.futures import wait as fut_wait

        deadline = time.monotonic() + max(
            getattr(self.config, "replay_drain_s", 30.0), 0.0
        )
        remaining = list(futs)
        while remaining and not self._stop_evt.is_set():
            left = deadline - time.monotonic()
            if left <= 0:
                break
            done, not_done = fut_wait(remaining, timeout=min(left, 0.1))
            remaining = list(not_done)
            with self._lock:
                if self._pending:
                    return  # a replay died again: next round handles it

    # ------------------------------------------------------------- watchdog

    def start(self) -> "RecoveryController":
        """Background watchdog: polls check() every watchdog_interval_s
        (wakeable early by a failure trigger). Tests with fake clocks
        never call this — check()/run_cycle() are the whole machine."""
        if self._worker is None or not self._worker.is_alive():
            self._stop_evt = threading.Event()
            self._worker = threading.Thread(
                target=self._watchdog_loop,
                args=(self._stop_evt, self._wake),
                name="recovery-watchdog", daemon=True,
            )
            self._worker.start()
        return self

    def _watchdog_loop(self, stop_evt, wake) -> None:
        interval = max(self.config.watchdog_interval_s, 0.05)
        while not stop_evt.is_set():
            wake.wait(interval)
            wake.clear()
            if stop_evt.is_set():
                return
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog must survive
                log.exception("recovery watchdog pass failed")

    def stop(self) -> None:
        self.shutdown_for_drain(2.0)

    def shutdown_for_drain(self, grace_s: float = 2.0) -> None:
        """GracefulShutdown interplay (ISSUE 11 satellite): called BEFORE
        batcher.drain() so a SIGTERM arriving mid-REINIT cannot deadlock
        the drain on replayed batches — the watchdog stops, the active
        cycle aborts at its next phase boundary, and anything still
        captured fails UNAVAILABLE (clients reroute; this replica is
        going away regardless). Bounded by min(grace, 2s)."""
        self._stop_evt.set()
        self._wake.set()
        bound = min(max(grace_s, 0.0), 2.0)
        if self._worker is not None:
            self._worker.join(timeout=bound)
            self._worker = None
        t_end = time.monotonic() + bound
        while self._cycle_mutex.locked() and time.monotonic() < t_end:
            time.sleep(0.02)
        err = DeviceWedgedError(
            "server draining during device recovery; retry against "
            "another backend"
        )
        for unit in self._drain_pending():
            self._apply_fails([(it, err) for it in unit])
        with self._lock:
            self._trigger = None
        if self._state != SERVING:
            self._enter(SERVING, trigger="drain_abort")

    # ------------------------------------------------------------- plumbing

    def _lifecycle(self):
        if self.lifecycle is not None:
            return self.lifecycle
        return getattr(self.impl, "lifecycle", None)

    def _enter(self, state: str, **detail) -> None:
        now = self._clock()
        with self._lock:
            self._state = state
            self._state_since = now
            self._events.append({
                "t": round(now, 3), "state": state, **detail,
            })
        log.info("recovery -> %s %s", state, detail or "")

    @staticmethod
    def _safe(fn, default=None):
        try:
            return fn()
        except Exception:  # noqa: BLE001 — collaborator quirks must not
            log.exception("recovery collaborator call failed")  # kill a cycle
            return default

    # ------------------------------------------------------------- surfaces

    def _mttr_block_locked(self) -> dict:
        """Per-cycle MTTR history (ring) + summary stats. Lock held."""
        hist = list(self._mttr_ring)
        vals = [h["mttr_s"] for h in hist]
        return {
            "cycles": len(hist),
            "last_s": vals[-1] if vals else None,
            "mean_s": round(sum(vals) / len(vals), 4) if vals else None,
            "max_s": max(vals) if vals else None,
            "history": hist,
        }

    def snapshot(self) -> dict:
        """The /recoveryz body, the `recovery` /monitoring block, and the
        dts_tpu_recovery_* Prometheus source."""
        now = self._clock()
        cfg = self.config
        with self._lock:
            return {
                "enabled": True,
                "state": self._state,
                "state_age_s": round(now - self._state_since, 3),
                "pending_replay_units": len(self._pending),
                "pending_replay_items": sum(len(u) for u in self._pending),
                "counters": {
                    "quarantines": self.quarantines,
                    "reinits": self.reinits,
                    "cycles_completed": self.cycles_completed,
                    "device_failures": self.device_failures,
                    "replayed_items": self.replayed_items,
                    "replay_budget_exhausted": self.replay_budget_exhausted,
                    "poisoned_requests": self.poisoned_requests,
                    "bisections": self.bisections,
                    "watchdog_wedge_trips": self.watchdog_wedge_trips,
                    "thread_deaths": self.thread_deaths,
                },
                "last_cycle": self._last_cycle,
                "mttr": self._mttr_block_locked(),
                "events": list(self._events),
                "config": {
                    "watchdog_interval_s": cfg.watchdog_interval_s,
                    "wedge_quarantine_s": cfg.wedge_quarantine_s,
                    "replay_budget": cfg.replay_budget,
                    "poison_kills": cfg.poison_kills,
                    "bisect_after_kills": cfg.bisect_after_kills,
                    "reinit_warmup": cfg.reinit_warmup,
                    "reinit_clear_backend": cfg.reinit_clear_backend,
                    "replay_drain_s": cfg.replay_drain_s,
                    "max_cycle_rounds": cfg.max_cycle_rounds,
                },
            }
