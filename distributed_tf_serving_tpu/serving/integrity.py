"""End-to-end data-integrity plane (ISSUE 20).

Silent corruption — a flipped bit on the wire, a bad DMA on device->host
readback, an ALU that miscomputes one lane — is the one failure class the
rest of this stack was blind to: every other plane detects *loud*
failures (errors, timeouts, crashes) while a corrupted score serves with
status OK. This module is the detection ladder, three layers deep, each
escalating into machinery that already exists instead of inventing a new
recovery path:

1. **Wire integrity** — CRC32C sidecars (codec.crc_sidecar) over tensor
   bytes in gRPC metadata, both directions. The server verifies
   ``x-dts-input-crc`` at decode and fails ONLY the corrupted request
   (INVALID_ARGUMENT, ``corrupt-wire`` detail) — never the coalesced
   batch. The server stamps ``x-dts-score-crc`` trailing metadata that an
   opted-in client verifies before merge; a mismatch steers (scoreboard
   kind="corrupt") and fails over, like overload pushback — never
   ejection on first hit.

2. **Readback sanity screens** — a post-D2H screen in the batcher
   completer checks delivered score rows for NaN/Inf (and an optional
   plausible range). A failing ROW fails its own request
   (IntegrityScreenError -> UNAVAILABLE) while batchmates deliver — the
   per-item machinery from the poisoned-input work. Trips past
   ``screen_trips_per_window`` escalate to the RecoveryController
   (trigger ``output_corrupt``) because systematic garbage readback means
   the executor, not the request, is sick.

3. **Shadow verification** (headline) — a sampled fraction of batches
   re-executes through the SAME jitted entry and the two host results
   are compared bit-identically. XLA programs are deterministic per
   (program, input) on one device, so ANY divergence is hardware
   miscomputation or readback corruption: the batch is captured for
   replay via the recovery cycle, and the replica marks itself
   ``suspect`` — gossiped fleet-wide so the router steers around it.

The plane is off by default and costs one attribute read per hook when
disabled. All state is process-local and lock-guarded; hooks are called
from the batcher thread, transports, and the REST thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .. import codec

__all__ = [
    "IntegrityPlane",
    "IntegrityScreenError",
    "OutputCorruptError",
]


class OutputCorruptError(RuntimeError):
    """The executor's outputs can no longer be trusted: shadow
    re-execution diverged bit-for-bit, or readback screens tripped past
    threshold. recovery.device_fatal() recognizes the marker attribute
    and runs the quarantine -> reinit -> replay cycle with trigger
    ``output_corrupt`` — the device never reported dead, but its data
    path did."""

    integrity_corrupt = True


class IntegrityScreenError(RuntimeError):
    """One delivered row failed the post-readback sanity screen (NaN/Inf
    or out of the configured plausible range). Scoped to the single
    request that owns the row — batchmates deliver normally. Translates
    to UNAVAILABLE so a resilient client retries/fails over.

    The message must never contain a recovery _FATAL_MARKERS substring
    (e.g. the grpc DATA-LOSS code name spelled with an underscore):
    this error is per-row by design and must not read as a dead device.
    """


class IntegrityPlane:
    """State + policy for the three detection layers of one server.

    Collaborators are late-bound the same way the recovery controller's
    are: the batcher reads ``batcher.integrity``, the service impl reads
    ``impl.integrity``, transports reach the plane through the impl.
    A fake clock makes the screen-trip window testable without sleeps.
    """

    def __init__(self, config, clock=time.monotonic):
        self.config = config
        self._clock = clock
        self._lock = threading.Lock()
        # Shadow sampler: deterministic fraction accumulator (no RNG —
        # the same traffic always samples the same batches) plus an
        # on-demand audit counter fed by POST /integrityz/audit.
        self._acc = 0.0
        self._pending_audits = 0
        # Counters (monotonic; Prometheus reads them off snapshot()).
        self.wire_verified = 0
        self.wire_rejected = 0
        self.responses_stamped = 0
        self.screen_trips = 0
        self.shadow_batches = 0
        self.shadow_mismatches = 0
        self.audits_requested = 0
        self.audits_run = 0
        self.escalations = 0
        # Suspect verdict: gossiped fleet-wide via the replica record;
        # cleared after `suspect_clear_passes` consecutive clean shadow
        # comparisons (evidence the data path computes correctly again).
        self.suspect = False
        self.suspect_reason: str | None = None
        self._clean_passes = 0
        # Screen-trip timestamps inside the sliding window.
        self._trips: deque[float] = deque()
        self._events: deque[dict] = deque(
            maxlen=max(int(getattr(config, "history_events", 64)), 8)
        )

    # -------------------------------------------------------------- events

    def _event(self, kind: str, **detail) -> None:
        self._events.append({"t": self._clock(), "kind": kind, **detail})

    # -------------------------------------------- layer 1: wire checksums

    def verify_inputs(self, arrays: dict, sidecar: str) -> list[str]:
        """Server-side request verify: decoded input arrays against the
        client's ``x-dts-input-crc`` stamp. Returns the mismatched names
        (empty = clean); a malformed sidecar IS a mismatch. The caller
        fails only the one request that carried the stamp."""
        try:
            bad = codec.verify_crc_sidecar(arrays, sidecar)
        except codec.CodecError as e:
            bad = [f"sidecar: {e}"]
        with self._lock:
            if bad:
                self.wire_rejected += 1
                self._event("wire_reject", names=list(bad))
            else:
                self.wire_verified += 1
        return bad

    def response_sidecar(self, outputs_map) -> str | None:
        """Server-side response stamp: CRC every output tensor in the
        encoded response (the client checks the same decoded-ndarray
        canonical form, so tensor_content / repeated fields / the int8
        score wire all verify identically). None when nothing encodes —
        stamping is advisory and must never fail a good response."""
        try:
            decoded = {
                name: codec.to_ndarray(tp)
                for name, tp in outputs_map.items()
            }
            sidecar = codec.crc_sidecar(decoded)
        except Exception:  # noqa: BLE001 — advisory stamp
            return None
        if not sidecar:
            return None
        with self._lock:
            self.responses_stamped += 1
        return sidecar

    # ------------------------------------------ layer 2: readback screens

    def screen_reason(self, row: np.ndarray) -> str | None:
        """Why one delivered row fails the sanity screen, or None. Only
        float outputs can carry NaN/Inf; the range check is opt-in
        ((0, 0) disables it — scores are model-specific)."""
        if row.dtype.kind != "f":
            return None
        if not np.isfinite(row).all():
            return "non-finite score (nan/inf) after readback"
        lo, hi = self.config.screen_min, self.config.screen_max
        if (lo, hi) != (0.0, 0.0) and row.size:
            mn, mx = float(row.min()), float(row.max())
            if mn < lo or mx > hi:
                return (
                    f"score outside plausible range [{lo}, {hi}]: "
                    f"observed [{mn:.6g}, {mx:.6g}]"
                )
        return None

    def note_screen_trip(self, reason: str) -> None:
        with self._lock:
            self.screen_trips += 1
            self._trips.append(self._clock())
            self._event("screen_trip", reason=reason)

    def screen_escalation_due(self) -> bool:
        """True when trips inside the sliding window crossed the
        threshold; consumes the window so one burst escalates once."""
        with self._lock:
            now = self._clock()
            horizon = now - self.config.screen_window_s
            while self._trips and self._trips[0] < horizon:
                self._trips.popleft()
            if len(self._trips) < self.config.screen_trips_per_window:
                return False
            self._trips.clear()
            return True

    def maybe_escalate_screen(self, recovery) -> bool:
        """Post-delivery hook: when the trip window overflowed, mark
        suspect and request a recovery cycle. The empty group is
        deliberate — the tripped rows already failed individually; the
        cycle exists to reinit the executor before the NEXT batch."""
        if not self.screen_escalation_due():
            return False
        self._escalate("screen trips crossed threshold")
        if recovery is not None:
            recovery.take_group([], OutputCorruptError(
                "readback screen trips crossed "
                f"{self.config.screen_trips_per_window}/"
                f"{self.config.screen_window_s:g}s — executor output "
                "path no longer trusted"
            ))
        return True

    # --------------------------------------- layer 3: shadow verification

    def request_audit(self, batches: int = 1) -> int:
        """POST /integrityz/audit: force the next `batches` eligible
        batches through shadow verification regardless of
        shadow_fraction. Returns the number of audits now pending."""
        with self._lock:
            self.audits_requested += batches
            self._pending_audits += batches
            self._event(f"audit_requested x{batches}")
            return self._pending_audits

    def want_shadow(self) -> bool:
        """Dispatch-side sampler. Pending audits fire first; otherwise a
        deterministic accumulator realizes shadow_fraction exactly (one
        shadow per 1/fraction batches, no RNG)."""
        with self._lock:
            if self._pending_audits > 0:
                self._pending_audits -= 1
                self.audits_run += 1
                return True
            f = self.config.shadow_fraction
            if f <= 0.0:
                return False
            self._acc += f
            if self._acc >= 1.0:
                self._acc -= 1.0
                return True
            return False

    def shadow_compare(self, primary, shadow) -> None:
        """Bit-identity compare of two host output lists from the same
        jitted entry over the same inputs. Raises OutputCorruptError on
        ANY divergence (shape, dtype, or payload byte); a clean pass
        counts toward suspect rehabilitation."""
        mismatch = None
        if len(primary) != len(shadow):
            mismatch = (
                f"output arity diverged: {len(primary)} vs {len(shadow)}"
            )
        else:
            for i, (a, b) in enumerate(zip(primary, shadow)):
                a = np.ascontiguousarray(a)
                b = np.ascontiguousarray(b)
                if a.dtype != b.dtype or a.shape != b.shape:
                    mismatch = (
                        f"output {i} meta diverged: "
                        f"{a.dtype}{a.shape} vs {b.dtype}{b.shape}"
                    )
                    break
                if a.tobytes() != b.tobytes():
                    mismatch = f"output {i} bytes diverged"
                    break
        with self._lock:
            self.shadow_batches += 1
        if mismatch is None:
            self._note_clean_shadow()
            return
        with self._lock:
            self.shadow_mismatches += 1
            self._event("shadow_mismatch", detail=mismatch)
        self._escalate(f"shadow mismatch: {mismatch}")
        raise OutputCorruptError(
            "integrity shadow verification mismatch — same program, same "
            f"inputs, different bits ({mismatch}); capturing batch for "
            "replay"
        )

    # ------------------------------------------------------ suspect state

    def _escalate(self, reason: str) -> None:
        with self._lock:
            self.escalations += 1
            self.suspect = True
            self.suspect_reason = reason
            self._clean_passes = 0
            self._event("escalation", reason=reason)

    def _note_clean_shadow(self) -> None:
        with self._lock:
            if not self.suspect:
                return
            self._clean_passes += 1
            if self._clean_passes >= self.config.suspect_clear_passes:
                self.suspect = False
                self.suspect_reason = None
                self._clean_passes = 0
                self._event("suspect_cleared")

    # ---------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "suspect": self.suspect,
                "suspect_reason": self.suspect_reason,
                "clean_passes": self._clean_passes,
                "wire": {
                    "enabled": bool(self.config.wire_checksums),
                    "inputs_verified": self.wire_verified,
                    "inputs_rejected": self.wire_rejected,
                    "responses_stamped": self.responses_stamped,
                },
                "screen": {
                    "enabled": bool(self.config.screen),
                    "trips": self.screen_trips,
                    "window_trips": len(self._trips),
                    "trips_per_window": self.config.screen_trips_per_window,
                    "window_s": self.config.screen_window_s,
                },
                "shadow": {
                    "fraction": self.config.shadow_fraction,
                    "batches": self.shadow_batches,
                    "mismatches": self.shadow_mismatches,
                    "audits_requested": self.audits_requested,
                    "audits_run": self.audits_run,
                    "audits_pending": self._pending_audits,
                },
                "escalations": self.escalations,
                "events": list(self._events),
            }
