"""PredictionService semantics, transport-free.

Implements the server-side contract the reference reaches only through the
external tensorflow_model_server (SURVEY.md §3.5): ModelSpec resolution with
latest-version default (model.proto:12-14), signature lookup, input
validation against the signature, output_filter selection
(predict.proto:23-30), and the Classify/Regress/MultiInference Example path.
The gRPC layer (server.py) is a thin adapter over this class, so the same
logic is testable without sockets and reusable from an in-process client.

Error taxonomy (per-RPC status codes — the failure-detection obligation from
SURVEY.md §5): unknown model/version -> NOT_FOUND; malformed tensors,
signature mismatches, bad Examples -> INVALID_ARGUMENT; oversized batches ->
RESOURCE_EXHAUSTED (wired to codes in server.py via ServiceError.code).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import codec, faults
from ..utils import tracing
from ..utils.tracing import request_trace
from ..models.registry import (
    ModelNotFoundError,
    Servable,
    ServableRegistry,
    Signature,
    SignatureNotFoundError,
    VersionNotFoundError,
)
from ..proto import serving_apis_pb2 as apis
from ..proto import tf_framework_pb2 as fw
from . import cascade as cascade_mod
from .batcher import (
    BatchTooLargeError,
    DeviceWedgedError,
    DynamicBatcher,
    PoisonedInputError,
    QueueOverloadError,
    RequestDeadlineError,
)
from .example_codec import ExampleDecodeError, decode_input
from .integrity import IntegrityScreenError

SIGNATURE_DEF_FIELD = "signature_def"


class ServiceError(Exception):
    """Carries a grpc-compatible status code name ('NOT_FOUND', ...).
    `retry_after_ms`, when set (overload-plane refusals), is the pushback
    hint the transport adapters forward in trailing metadata (gRPC) or
    the Retry-After header (REST)."""

    def __init__(self, code: str, message: str,
                 retry_after_ms: int | None = None):
        super().__init__(message)
        self.code = code
        self.retry_after_ms = retry_after_ms


def _wrap_lookup(fn):
    try:
        return fn()
    except (ModelNotFoundError, VersionNotFoundError, SignatureNotFoundError) as e:
        raise ServiceError("NOT_FOUND", str(e)) from e


class PredictionServiceImpl:
    """Registry + batcher -> the five PredictionService RPCs."""

    def __init__(self, registry: ServableRegistry, batcher: DynamicBatcher):
        self.registry = registry
        self.batcher = batcher
        # Flipped by build_stack around its load+warmup phase; the
        # grpc.health.v1 servicer reports the overall server NOT_SERVING
        # while False. Default True: directly-constructed impls (tests,
        # in-process embedding) are serving the moment they exist.
        self.warmup_complete = True
        # Optional sampled PredictionLog writer (serving/request_log.py);
        # assign a RequestLogger to enable — both transports and all four
        # RPC families flow through these entry points.
        self.request_logger = None
        # Optional runtime model-list reconciler (server.ModelLifecycle,
        # set by --model-config-file deployments): when present,
        # HandleReloadConfigRequest carries upstream's FULL semantics —
        # the supplied model list replaces the served set.
        self.model_lifecycle = None
        # name -> (base_path, model_kind) for single-model watcher mode:
        # lets label-only reloads accept a config that re-states the
        # CURRENT source (deploy tools replay their full config) while
        # rejecting an actual move this mode cannot honor.
        self.served_sources: dict[str, tuple[str, str]] = {}
        # Graceful drain (serving/server.py GracefulShutdown): True once a
        # SIGTERM/shutdown started — new inference admissions are refused
        # with UNAVAILABLE "draining" while queued + in-flight work
        # completes, and the grpc.health.v1 servicer reports NOT_SERVING.
        self.draining = False
        # Continuous-freshness lifecycle plane (serving/lifecycle.py):
        # when a LifecycleController is set, DEFAULT version resolution
        # of its model consults the canary router (requests pinning a
        # version or label are never touched). None (default) costs one
        # attribute read per resolution.
        self.lifecycle = None
        # The single-model version watcher, when one owns this impl's
        # model (build_stack sets it): the /monitoring `versions` block
        # reads loaded/on-disk/blacklist/pin state from it — present
        # whether or not the lifecycle controller is armed.
        self.version_watcher = None
        # Device-failure recovery plane (serving/recovery.py): when a
        # RecoveryController is set, the grpc.health.v1 servicer reports
        # NOT_SERVING through its quarantine/reinit/replay cycle and
        # GET /recoveryz serves its snapshot. None (default) costs one
        # attribute read where consulted.
        self.recovery = None
        # Kernel/quantization plane (ops/autotune.py, ISSUE 12): when a
        # KernelManager is set (build_stack attaches the same object to
        # the batcher), /monitoring's `kernels` block and the
        # dts_tpu_kernel_* Prometheus series read it, and — with its
        # int8_score_wire knob on — Predict responses for clients that
        # sent x-dts-score-wire: int8 carry the score tensor as DT_INT8
        # plus (scale, min) sidecar outputs. None (default) costs one
        # attribute read where consulted.
        self.kernels = None
        # Mesh serving mode (ISSUE 13): the ShardedExecutor installed as
        # the batcher's run_fn, when serving spans a device mesh.
        # /monitoring's `mesh` block and the dts_tpu_mesh_* Prometheus
        # series read its snapshot; None (default) = single-chip.
        self.mesh_executor = None
        # Elastic mesh serving (ISSUE 15): the ElasticController driving
        # runtime split switches, when [elastic] armed the ladder. The
        # `elastic` /monitoring section and dts_tpu_elastic_* Prometheus
        # series read through it; None (default) = static split (or no
        # mesh at all).
        self.elastic = None
        # Multi-stage ranking cascade (serving/cascade.py, ISSUE 19):
        # when a CascadeOrchestrator is set, score-only-filtered Predicts
        # big enough to prune run retrieval->rank in one RPC — stage-1
        # prune on device, full model over the survivors, provenance in
        # the response. None (default) costs one attribute read per
        # Predict.
        self.cascade = None
        # Fleet robustness plane (fleet/replica.py, ISSUE 17): the
        # ReplicaFleetPlane (gossip membership + rollout follower) when
        # [fleet] armed it. GET /fleetz and the dts_tpu_fleet_*
        # Prometheus series read through it; None (default) costs one
        # attribute read where consulted.
        self.fleet = None
        # Data-integrity plane (serving/integrity.py, ISSUE 20): when an
        # IntegrityPlane is set (build_stack attaches the same object to
        # the batcher), x-dts-input-crc request stamps are verified at
        # decode (mismatch fails ONLY that request, INVALID_ARGUMENT with
        # a corrupt-wire detail), responses are stamped with
        # x-dts-score-crc trailing metadata, and GET /integrityz serves
        # its snapshot. None (default) costs one attribute read per hook.
        self.integrity = None
        # Streamed sub-batch results (ISSUE 9): default server-side split
        # size (candidates per sub-batch) for PredictStream. 0 = no split
        # (one chunk per request — streaming stays wire-available but the
        # behavior change is off); a request may override via the
        # x-dts-stream-chunk metadata the transport adapters thread in.
        self.stream_chunk_candidates = 0
        # Reusable encode scratch ([transport] response_arena): when True,
        # response encodes run through a per-thread codec.EncodeArena
        # (contiguity/widen copies and the Example decoder's dense batch
        # reuse one backing allocation) and each PredictStream reuses ONE
        # chunk message. Off by default = historical allocate-per-call.
        self.response_arena = False
        self._arenas = threading.local()

    def _arena(self):
        """The calling thread's EncodeArena, or None when the plane is
        off. Per-thread: arenas are single-owner scratch by design."""
        if not self.response_arena:
            return None
        arena = getattr(self._arenas, "arena", None)
        if arena is None:
            arena = self._arenas.arena = codec.EncodeArena()
        return arena

    def pipeline_stats(self) -> dict | None:
        """Continuous-batching pipeline snapshot (configured depth /
        in-flight window, live per-bucket occupancy, overlap fraction) —
        the `pipeline` block in /monitoring and the dts_tpu_pipeline_*
        Prometheus series. Always available: this is core batcher state,
        not a gated plane."""
        fn = getattr(self.batcher, "pipeline_stats", None)
        return fn() if callable(fn) else None

    def _log_request(self, kind: str, request) -> None:
        if self.request_logger is not None:
            self.request_logger.maybe_log(kind, request)

    # ----------------------------------------------------------- cache plane

    def cache_stats(self) -> dict | None:
        """Cache-plane snapshot (per-model hit/miss/coalesced/eviction
        counters, occupancy, config) — the body of GET /cachez and the
        `cache` block in /monitoring. None when no score cache is armed,
        so both surfaces can distinguish "disabled" from "cold"."""
        cache = getattr(self.batcher, "score_cache", None)
        return cache.snapshot() if cache is not None else None

    def row_cache_stats(self) -> dict | None:
        """Row-granular cache snapshot (per-row hit/miss/coalesced
        counters, rows_executed vs rows_requested, occupancy) — the
        `row_cache` block in GET /cachez and /monitoring and the
        dts_tpu_cache_row_* Prometheus series. None when no row cache is
        armed ([cache] row_granular=false)."""
        rc = getattr(self.batcher, "row_cache", None)
        if rc is None:
            return None
        snap = rc.snapshot()
        stats = getattr(self.batcher, "stats", None)
        if stats is not None:
            snap["batcher"] = {
                "row_batches": stats.row_batches,
                "rows_requested": stats.rows_requested,
                "rows_executed": stats.rows_executed,
                "row_full_hit_batches": stats.row_full_hit_batches,
            }
        return snap

    def cache_flush(self, model: str | None = None) -> int:
        """Operator flush control: drop every cached score (or one
        model's), generation-bumped so in-flight fills of the flushed
        entries die too — the row-granular tier flushes with the request
        tier (one operator surface, both stores). Returns the total
        number of entries dropped."""
        cache = getattr(self.batcher, "score_cache", None)
        row_cache = getattr(self.batcher, "row_cache", None)
        if cache is None and row_cache is None:
            raise ServiceError(
                "FAILED_PRECONDITION",
                "no score cache is configured ([cache] enabled=false)",
            )
        dropped = cache.flush(model) if cache is not None else 0
        if row_cache is not None:
            dropped += row_cache.flush(model)
        return dropped

    def overload_stats(self) -> dict | None:
        """Overload-plane snapshot (adaptive limit, pressure state, shed /
        doomed / brownout counters) — the `overload` block in /monitoring
        and the dts_tpu_overload_* Prometheus series. None when no
        controller is armed ([overload] enabled=false)."""
        ctrl = getattr(self.batcher, "overload", None)
        return ctrl.snapshot() if ctrl is not None else None

    def utilization_stats(self, window_s: float | None = None) -> dict | None:
        """Utilization-plane snapshot (occupancy ledger + gap waterfall +
        live achieved_fraction_of_device_limit) — the body of GET /utilz,
        the `utilization` block in /monitoring, and the
        dts_tpu_utilization_* Prometheus series. None when no ledger is
        armed ([utilization] enabled=false)."""
        ledger = getattr(self.batcher, "utilization", None)
        return ledger.snapshot(window_s) if ledger is not None else None

    def quality_stats(
        self, model: str | None = None, version: int | None = None
    ) -> dict | None:
        """Quality-plane snapshot (per-(model, version) score sketches,
        PSI/JS drift vs reference and between live versions, label-join
        AUC/calibration, exemplar counters) — the body of GET /qualityz,
        the `quality` block in /monitoring, and the dts_tpu_quality_*
        Prometheus series. None when no monitor is armed ([quality]
        enabled=false)."""
        monitor = getattr(self.batcher, "quality", None)
        if monitor is None:
            return None
        return monitor.snapshot(model=model, version=version)

    def quality_ingest_labels(self, items) -> dict:
        """Label-feedback ingest (POST /labelz): join (id, label, ts)
        records onto the score reservoir. Raises FAILED_PRECONDITION when
        the plane is off, INVALID_ARGUMENT on malformed items."""
        monitor = getattr(self.batcher, "quality", None)
        if monitor is None:
            raise ServiceError(
                "FAILED_PRECONDITION",
                "no quality monitor is configured ([quality] enabled=false)",
            )
        try:
            return monitor.ingest_labels(items)
        except (TypeError, ValueError) as e:
            raise ServiceError("INVALID_ARGUMENT", str(e)) from e

    def quality_pin_reference(self) -> dict:
        """Pin the current windowed score distributions as the drift
        reference (POST /qualityz/snapshot) and persist the artifact when
        a reference_file is configured."""
        monitor = getattr(self.batcher, "quality", None)
        if monitor is None:
            raise ServiceError(
                "FAILED_PRECONDITION",
                "no quality monitor is configured ([quality] enabled=false)",
            )
        return monitor.pin_reference()

    def lifecycle_stats(self) -> dict | None:
        """Lifecycle-plane snapshot (state machine, canary routing
        fractions/counters, publish/promote/rollback history, watcher
        blacklist/pin state) — the body of GET /lifecyclez, the
        `lifecycle` block in /monitoring, and the dts_tpu_lifecycle_*
        Prometheus series. None when no controller is armed ([lifecycle]
        enabled=false)."""
        lc = self.lifecycle
        return lc.snapshot() if lc is not None else None

    def recovery_stats(self) -> dict | None:
        """Recovery-plane snapshot (state machine, quarantine/replay/
        bisection counters, last-cycle MTTR evidence) — the body of
        GET /recoveryz, the `recovery` block in /monitoring, and the
        dts_tpu_recovery_* Prometheus series. None when no controller is
        armed ([recovery] enabled=false)."""
        rec = self.recovery
        return rec.snapshot() if rec is not None else None

    def cascade_stats(self) -> dict | None:
        """Cascade-plane snapshot (per-stage latency totals, pruned/
        survivor/fallback counters, observed survivor fraction, survivor
        bucket histogram) — the body of GET /cascadez, the `cascade`
        block in /monitoring, and the dts_tpu_cascade_* Prometheus
        series. None when the plane is off ([cascade] enabled=false)."""
        casc = self.cascade
        return casc.snapshot() if casc is not None else None

    def fleet_stats(self) -> dict | None:
        """Fleet-plane snapshot (gossip membership view + exchange
        counters, rollout-follower state) — the body of GET /fleetz, the
        `fleet` block in /monitoring, and the dts_tpu_fleet_* Prometheus
        series. None when the plane is off ([fleet] enabled=false)."""
        fl = self.fleet
        return fl.fleet_stats() if fl is not None else None

    def integrity_stats(self) -> dict | None:
        """Integrity-plane snapshot (wire verify/reject counters, screen
        trips + window state, shadow batches/mismatches/audits, suspect
        verdict, escalations, bounded event history) — the body of
        GET /integrityz, the `integrity` block in /monitoring, and the
        dts_tpu_integrity_* Prometheus series. None when the plane is
        off ([integrity] enabled=false)."""
        integ = self.integrity
        return integ.snapshot() if integ is not None else None

    def response_crc_sidecar(self, resp) -> str | None:
        """The x-dts-score-crc trailing-metadata value for one encoded
        PredictResponse, or None when the plane (or its wire layer) is
        off. Called by the transport adapters after the handler returns —
        the stamp covers the exact tensors that ride the wire."""
        integ = self.integrity
        if integ is None or not integ.config.wire_checksums:
            return None
        return integ.response_sidecar(resp.outputs)

    def kernels_stats(self) -> dict | None:
        """Kernel-plane snapshot (per-bucket decision table, measured
        speedups + accuracy-gate outcomes, quantized/pallas batch
        counters) — the `kernels` block in /monitoring and the
        dts_tpu_kernel_* Prometheus series. None when no manager is
        armed ([kernels] enabled=false)."""
        kern = self.kernels
        return kern.snapshot() if kern is not None else None

    def mesh_stats(self, utilization: dict | None = None) -> dict | None:
        """Mesh-mode snapshot (mesh geometry + device list, executor
        batch/pad counters, layout source per served model, per-device
        occupancy attribution when the utilization plane rides along) —
        the `mesh` block in /monitoring and the dts_tpu_mesh_*
        Prometheus series. None when serving is single-chip.

        `utilization` (an already-computed utilization_stats() snapshot)
        avoids recomputing the ledger's O(ring log ring) waterfall merge
        when the caller renders both blocks in one pass (the Prometheus
        scrape and the full /monitoring snapshot do)."""
        ex = self.mesh_executor
        if ex is None:
            return None
        snap = ex.snapshot()
        ledger = getattr(self.batcher, "utilization", None)
        if ledger is not None:
            # The per-device attribution has ONE implementation — the
            # ledger's own snapshot (OccupancyLedger.devices +
            # per_device) — lifted here, never rebuilt: two copies of
            # the spmd_uniform math would drift. An embedded ledger that
            # was never device-labeled (build_stack labels it; direct
            # construction may not) adopts the mesh's device list first
            # (idempotent), which forces one fresh snapshot.
            try:
                usnap = utilization
                if getattr(ledger, "devices", None) is None:
                    ledger.devices = list(snap["devices"])
                    usnap = None  # pre-label snapshot lacks per_device
                if usnap is None:
                    usnap = ledger.snapshot()
                if usnap.get("per_device") is not None:
                    snap["per_device"] = usnap["per_device"]
                    snap["occupancy_attribution"] = usnap.get(
                        "occupancy_attribution", "spmd_uniform"
                    )
            except Exception:  # noqa: BLE001 — telemetry, never a dependency
                pass
        return snap

    def elastic_stats(self, mesh: dict | None = None) -> dict | None:
        """Elastic-plane snapshot (current split, ladder, per-split serve
        counters + live in-flight, switch history ring, controller
        decision state) — the `elastic` /monitoring section and the
        dts_tpu_elastic_* Prometheus series. None when the plane is off
        ([elastic] enabled=false). The same block also rides inside
        mesh_stats()//meshz as snapshot()['elastic']; `mesh` (an
        already-computed mesh_stats() snapshot) lifts it from there
        instead of re-walking the executor locks + history ring when the
        caller renders both blocks in one pass (the Prometheus scrape
        and the full /monitoring snapshot do — the mesh_stats
        (utilization=) precedent)."""
        ctrl = self.elastic
        if ctrl is None:
            return None
        if mesh is not None and mesh.get("elastic") is not None:
            return mesh["elastic"]
        return ctrl.executor.elastic_snapshot()

    def versions_stats(self) -> dict | None:
        """Version-watcher snapshot (loaded versions, last reconcile
        pass's on-disk-ready view, blacklist/pin sets, failed load
        attempts) — the /monitoring `versions` block. Available whenever
        a single-model watcher owns this impl's model, lifecycle armed
        or not (the blacklist/pin API is operator-callable on its own)."""
        watcher = self.version_watcher
        return watcher.snapshot() if watcher is not None else None

    def lifecycle_route(
        self, name: str, version, label, criticality: str | None
    ) -> int | None:
        """Canary-admission version override for one request, or None.
        Only DEFAULT resolutions of the lifecycle's own model are routed
        — an explicit version or label pin is the client's choice and
        the rollout must never second-guess it."""
        lc = self.lifecycle
        if lc is None or version is not None or label is not None \
                or name != lc.model:
            return None
        return lc.route(criticality)

    def _refuse_if_draining(self) -> None:
        """Drain-aware admission gate: once shutdown started, new
        inference work is refused (UNAVAILABLE, so fan-out clients reroute
        to another backend) while already-accepted work completes."""
        if self.draining:
            raise ServiceError(
                "UNAVAILABLE",
                "server is draining (shutdown in progress); retry against "
                "another backend",
            )

    def is_configured(self, name: str) -> bool:
        """True when this server is CONFIGURED to serve `name` (a watcher
        or lifecycle owns it), whether or not a version is ready yet — the
        one definition shared by GetModelStatus (START vs NOT_FOUND) and
        the grpc.health.v1 servicer (NOT_SERVING vs NOT_FOUND)."""
        lifecycle = self.model_lifecycle
        return name in self.served_sources or (
            lifecycle is not None
            and name in getattr(lifecycle, "configured_models", lambda: ())()
        )

    # ------------------------------------------------------------ resolution

    @staticmethod
    def _version_choice(model_spec: apis.ModelSpec) -> tuple[int | None, str | None]:
        """(version, label) from a ModelSpec, enforcing the upstream oneof:
        the real model.proto wraps version/version_label in oneof
        version_choice, so setting both is a client error there — here the
        vendored proto (reference parity) has no oneof, and the server
        enforces the exclusivity instead."""
        version = model_spec.version.value if model_spec.HasField("version") else None
        label = model_spec.version_label or None
        if version is not None and label is not None:
            raise ServiceError(
                "INVALID_ARGUMENT",
                "model_spec sets both version and version_label; they are a "
                "oneof upstream — choose one",
            )
        return version, label

    def _resolve(
        self, model_spec: apis.ModelSpec, criticality: str | None = None
    ) -> tuple[Servable, Signature]:
        if not model_spec.name:
            raise ServiceError("INVALID_ARGUMENT", "model_spec.name is required")
        version, label = self._version_choice(model_spec)
        routed = self.lifecycle_route(
            model_spec.name, version, label, criticality
        )
        if routed is not None:
            try:
                servable = self.registry.resolve(model_spec.name, routed)
            except (ModelNotFoundError, VersionNotFoundError):
                # The routed version vanished mid-swap (rollback racing
                # this request): fall back to the latest-version default
                # — a rollout action must never FAIL live traffic.
                servable = _wrap_lookup(
                    lambda: self.registry.resolve(model_spec.name)
                )
            span = tracing.current_span()
            if span is not None:
                span.attrs["lifecycle_version"] = servable.version
        else:
            servable = _wrap_lookup(
                lambda: self.registry.resolve(model_spec.name, version, label)
            )
        signature = _wrap_lookup(lambda: servable.signature(model_spec.signature_name))
        return servable, signature

    def _echo_spec(self, servable: Servable, signature_name: str) -> apis.ModelSpec:
        spec = apis.ModelSpec(name=servable.name, signature_name=signature_name)
        spec.version.value = servable.version
        return spec

    # --------------------------------------------------------------- Predict

    def _decode_and_validate(
        self, servable: Servable, signature: Signature, inputs
    ) -> dict[str, np.ndarray]:
        arrays: dict[str, np.ndarray] = {}
        specs = signature.input_specs
        for key in inputs:
            if key not in specs:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    f"unexpected input {key!r}; signature expects {sorted(specs)}",
                )
        n = None
        for name, spec in specs.items():
            if name not in inputs:
                if name == "dense_features":
                    continue  # optional (DLRM serves the 2-input contract too)
                raise ServiceError("INVALID_ARGUMENT", f"missing required input {name!r}")
            try:
                arr = codec.to_ndarray(inputs[name])
            except codec.CodecError as e:
                raise ServiceError("INVALID_ARGUMENT", f"input {name!r}: {e}") from e
            if arr.dtype != codec.dtype_to_numpy(spec.dtype):
                # Compact-wire widening: the transport is >half the single-
                # core request budget (round-4 echo floor: ~1.7 ms/MB), so
                # clients may pre-apply the SERVER's own first transforms
                # and ship the result: int32 ids already folded into the
                # vocab (the host fold is exact mod, models re-fold
                # idempotently) and bf16 weights (the models' compute-dtype
                # cast, round-to-nearest-even either side). Scores are
                # bit-identical to the wide encoding; anything else stays a
                # hard INVALID_ARGUMENT.
                # Widening is accepted ONLY where it re-states a transform
                # the server itself performs on this model, so equivalence
                # is structural, not hoped-for: int32 ids only where the
                # host fold runs (folds_ids_on_host — graph-executor models
                # consume raw int64), bf16 only for the weights input of a
                # model that consumes weights through its bf16 compute-
                # dtype cast (wide_deep/deepfm's f32 sparse-linear term and
                # DLRM's dense_features must arrive f32).
                model = servable.model
                widened = (
                    spec.dtype == fw.DataType.DT_INT64
                    and arr.dtype == np.int32
                    and name == "feat_ids"
                    and model.folds_ids_on_host
                ) or (
                    spec.dtype == fw.DataType.DT_FLOAT
                    and arr.dtype == codec.dtype_to_numpy(fw.DataType.DT_BFLOAT16)
                    and name == "feat_wts"
                    and model.wts_in_compute_dtype
                    and model.config.compute_dtype == "bfloat16"
                )
                if not widened:
                    raise ServiceError(
                        "INVALID_ARGUMENT",
                        f"input {name!r}: dtype {arr.dtype} != signature "
                        f"{fw.DataType.Name(spec.dtype)}",
                    )
                if name == "feat_ids" and arr.size:
                    # int32 ids ride the u24 transfer pack, which truncates
                    # to 3 LE bytes — an unfolded or NEGATIVE id would
                    # corrupt lookups before the device's re-fold could
                    # save it (-1 packs to 0xFFFFFF, a wrong-but-valid
                    # row). The compact contract is pre-folded ids in
                    # [0, vocab); enforce both ends (~60 us min+max pass).
                    lo, hi = int(arr.min()), int(arr.max())
                    if lo < 0 or hi >= model.config.vocab_size:
                        raise ServiceError(
                            "INVALID_ARGUMENT",
                            f"input {name!r}: int32 compact ids must be "
                            f"pre-folded into [0, "
                            f"{model.config.vocab_size}) (got range "
                            f"[{lo}, {hi}])",
                        )
            if spec.shape is None:
                # Unknown-rank signature (imported SavedModels): any shape
                # passes EXCEPT rank 0 — batching needs a candidate dim.
                if arr.ndim == 0:
                    raise ServiceError(
                        "INVALID_ARGUMENT",
                        f"input {name!r}: scalar tensor has no candidate dimension",
                    )
            elif (
                arr.ndim != len(spec.shape)
                or any(s is not None and s != d for s, d in zip(spec.shape, arr.shape))
            ):
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    f"input {name!r}: shape {arr.shape} incompatible with signature "
                    f"{spec.shape}",
                )
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    f"inconsistent candidate counts: {name!r} has {arr.shape[0]}, "
                    f"expected {n}",
                )
            arrays[name] = arr
        if n == 0:
            raise ServiceError("INVALID_ARGUMENT", "empty candidate batch")
        return arrays

    # Bounded wait: a wedged batcher must not permanently consume an RPC
    # handler thread / event-loop slot (first compile of a large bucket
    # through a remote-compile path can legitimately take tens of seconds).
    _BATCH_DEADLINE_S = 120.0

    @staticmethod
    def _translate_batcher_error(exc: Exception, fut) -> ServiceError:
        """ONE mapping from batcher failures to RPC status for both the
        threaded (_run) and coroutine (_run_async) paths — they must never
        return different codes for the same failure. Re-raises anything
        that is not a batcher failure."""
        if isinstance(exc, PoisonedInputError):
            # Recovery-plane bisection verdict: this request's bytes
            # deterministically kill the device executor — a DISTINCT,
            # non-retryable status (its batchmates were re-dispatched and
            # answered normally). Without this branch the ValueError
            # would re-raise and surface as INTERNAL.
            return ServiceError("INVALID_ARGUMENT", str(exc))
        if isinstance(exc, (BatchTooLargeError, QueueOverloadError)):
            # Overload-plane refusals (AdmissionRefusedError) carry a
            # retry-after-ms pushback hint; it rides the ServiceError so
            # the transport can attach it as trailing metadata.
            return ServiceError(
                "RESOURCE_EXHAUSTED", str(exc),
                retry_after_ms=getattr(exc, "retry_after_ms", None),
            )
        if isinstance(exc, DeviceWedgedError):
            return ServiceError("UNAVAILABLE", str(exc))
        if isinstance(exc, IntegrityScreenError):
            # Readback screen verdict (ISSUE 20): this request's score
            # rows came back NaN/Inf/implausible — retryable elsewhere
            # (a resilient client fails over), and per-row by design:
            # its batchmates delivered normally.
            return ServiceError("UNAVAILABLE", str(exc))
        if isinstance(exc, RequestDeadlineError):
            # The batcher shed the queued item itself (propagated client
            # deadline): the future already failed, nothing to withdraw.
            return ServiceError("DEADLINE_EXCEEDED", str(exc))
        if isinstance(exc, faults.InjectedFaultError):
            # Chaos at a batcher site (batcher.dispatch / readback) keeps
            # its injected status code instead of collapsing into the
            # RuntimeError->UNAVAILABLE catch-all below.
            return ServiceError(exc.code_name, str(exc))
        # Explicit tuple, not bare TimeoutError: asyncio.TimeoutError and
        # concurrent.futures.TimeoutError are aliases of the builtin only on
        # Python >= 3.11; on 3.10 a batcher deadline would surface as
        # INTERNAL and skip the fut.cancel() withdrawal below (round-3
        # advisor finding).
        import asyncio
        import concurrent.futures

        if isinstance(
            exc,
            (TimeoutError, asyncio.TimeoutError, concurrent.futures.TimeoutError),
        ):
            # Withdraw the work: a cancelled item is skipped by the batcher,
            # so an abandoned deadline never turns into a zombie dispatch
            # that delays everyone behind it.
            if fut is not None:
                fut.cancel()
            return ServiceError("DEADLINE_EXCEEDED", "batch execution timed out")
        if isinstance(exc, RuntimeError):
            return ServiceError("UNAVAILABLE", str(exc))
        raise exc

    @staticmethod
    def _clock_deadline(deadline_s: float | None) -> float | None:
        """Absolute give-up instant for a remaining-budget value, anchored
        at RPC ENTRY — captured before decode/validation, so pre-submit
        work spends the client's budget instead of silently extending it."""
        return None if deadline_s is None else time.perf_counter() + deadline_s

    @staticmethod
    def _budget_left(deadline_t: float | None) -> float | None:
        return None if deadline_t is None else deadline_t - time.perf_counter()

    def _effective_timeout(self, deadline_s: float | None) -> float:
        """Deadline propagation: the wait on the batcher future honors the
        CLIENT's remaining budget (context.time_remaining(), threaded down
        by the transport adapters) when it is tighter than the server's own
        wedge bound — a 2s-deadline Predict against a saturated batcher
        fails in ~2s, never the fixed 120s batch deadline. An already-
        expired deadline sheds before submit."""
        if deadline_s is None:
            return self._BATCH_DEADLINE_S
        if deadline_s <= 0:
            raise ServiceError(
                "DEADLINE_EXCEEDED", "client deadline already expired on arrival"
            )
        return min(deadline_s, self._BATCH_DEADLINE_S)

    def _run(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        output_keys: tuple[str, ...] | None = None,
        deadline_s: float | None = None,
        criticality: str | None = None,
        prune_k: int = 0,
    ) -> dict[str, np.ndarray]:
        timeout = self._effective_timeout(deadline_s)
        fut = None
        try:
            # The current span (the transport adapter's server root, when
            # tracing is on) rides into the batcher so its threads can
            # attach queue/device/readback child spans per request.
            fut = self.batcher.submit(
                servable, arrays, output_keys=output_keys,
                deadline_s=deadline_s, span=tracing.current_span(),
                criticality=criticality, _prune_k=prune_k,
            )
            out = fut.result(timeout=timeout)
            self._consume_future_degraded(fut)
            return out
        except Exception as e:  # noqa: BLE001 — translator re-raises non-batcher
            raise self._translate_batcher_error(e, fut) from e

    async def _run_async(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        output_keys: tuple[str, ...] | None = None,
        deadline_s: float | None = None,
        criticality: str | None = None,
        prune_k: int = 0,
    ) -> dict[str, np.ndarray]:
        """_run for coroutine servers (server.create_server_async): the
        batcher Future is awaited instead of blocked on, so one event-loop
        thread carries every in-flight RPC — on a single-core host the
        handler-thread-per-RPC model spends a measurable slice of the whole
        CPU budget on GIL hand-offs and context switches (round-3 load
        experiment: 72 threads cost ~15% of achievable QPS)."""
        import asyncio

        timeout = self._effective_timeout(deadline_s)
        fut = None
        try:
            fut = self.batcher.submit(
                servable, arrays, output_keys=output_keys,
                deadline_s=deadline_s, span=tracing.current_span(),
                criticality=criticality, _prune_k=prune_k,
            )
            out = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=timeout
            )
            self._consume_future_degraded(fut)
            return out
        except Exception as e:  # noqa: BLE001 — translator re-raises non-batcher
            raise self._translate_batcher_error(e, fut) from e

    @staticmethod
    def _consume_future_degraded(fut) -> None:
        """Row-granular brownout stale-serve (ISSUE 14): the batcher's
        completer runs on its own threads, so it cannot set this request's
        degraded contextvar — it leaves the marker on the Future instead,
        and THIS thread (the RPC's context) forwards it so the transport
        adapters emit x-dts-degraded exactly like a whole-request stale
        serve. One getattr per request when nothing is marked."""
        degraded = getattr(fut, "dts_degraded", None)
        if degraded is not None:
            from . import overload as overload_mod

            overload_mod.mark_degraded(degraded)

    def _predict_prepare(
        self, request: apis.PredictRequest, criticality: str | None = None,
        input_crc: str | None = None,
    ):
        """Shared front half of Predict: resolution, decode/validation,
        output_filter handling. Returns (servable, arrays, out_names).
        `criticality` reaches resolution so the lifecycle plane can route
        probe-lane (then a ramp of default-lane) traffic to a canary.
        `input_crc` is the client's x-dts-input-crc stamp (transport
        metadata): verified here — BEFORE the batcher ever sees the
        request — so a corrupted request fails alone, never the
        coalesced batch it would have joined."""
        servable, signature = self._resolve(request.model_spec, criticality)
        if signature.method_name != "tensorflow/serving/predict":
            raise ServiceError(
                "INVALID_ARGUMENT",
                f"signature {request.model_spec.signature_name!r} has method "
                f"{signature.method_name!r}; use the matching RPC instead of Predict",
            )
        with request_trace.span("predict.decode"):
            try:
                # Named fault site (faults.py): decode-stage chaos surfaces
                # with its injected status code, not as INTERNAL.
                faults.fire("decode")
            except faults.InjectedFaultError as e:
                raise ServiceError(e.code_name, str(e)) from e
            arrays = self._decode_and_validate(servable, signature, request.inputs)
        integ = self.integrity
        if (
            input_crc is not None
            and integ is not None
            and integ.config.wire_checksums
        ):
            bad = integ.verify_inputs(arrays, input_crc)
            if bad:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    "corrupt-wire: input tensor bytes do not match the "
                    f"request's x-dts-input-crc stamp on {bad} — the "
                    "payload was damaged in transit; resend",
                )

        sig_outputs = signature.output_names
        if request.output_filter:
            missing = [k for k in request.output_filter if k not in sig_outputs]
            if missing:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    f"output_filter names unknown tensors {missing}; have {sig_outputs}",
                )
            # Deduplicate (order-preserving): the in-place repeated-field
            # encode APPENDS, so a duplicated filter name would otherwise
            # emit doubled float_val lists against a single-n shape.
            out_names = list(dict.fromkeys(request.output_filter))
            # A filtered request pins the batcher's output selection: the
            # jitted entry returns (and the D2H link carries) only these
            # tensors — a score-only filter is what arms top-k compaction.
            fetch_keys = tuple(out_names)
        else:
            out_names = sig_outputs
            # None = all outputs: unfiltered requests share one executable
            # variant instead of keying the jit cache on the signature's
            # output list.
            fetch_keys = None
        return servable, arrays, out_names, fetch_keys

    def predict(
        self, request: apis.PredictRequest, deadline_s: float | None = None,
        criticality: str | None = None, int8_wire: bool = False,
        input_crc: str | None = None,
    ) -> apis.PredictResponse:
        self._refuse_if_draining()
        deadline_t = self._clock_deadline(deadline_s)
        servable, arrays, out_names, fetch_keys = self._predict_prepare(
            request, criticality, input_crc=input_crc
        )
        casc = self.cascade
        if casc is not None and casc.eligible(
            servable, fetch_keys, next(iter(arrays.values())).shape[0]
        ):
            # Multi-stage cascade (ISSUE 19): retrieval->rank in one RPC.
            # The provenance output rides the response like the int8-wire
            # sidecars — an extra tensor beyond the signature.
            with request_trace.span("predict.execute"):
                outputs = casc.run(
                    self, servable, arrays, fetch_keys, deadline_t,
                    criticality,
                )
            out_names = [*out_names, cascade_mod.STAGE_OUTPUT]
        else:
            with request_trace.span("predict.execute"):
                outputs = self._run(
                    servable, arrays, output_keys=fetch_keys,
                    deadline_s=self._budget_left(deadline_t),
                    criticality=criticality,
                )
        resp = self._predict_finish(
            request, servable, out_names, outputs, int8_wire=int8_wire
        )
        # Log only SUCCEEDED requests: the file's contract is direct
        # usability as a warmup file, and one malformed client request
        # must never poison a future version rollout (review finding).
        self._log_request("predict", request)
        return resp

    async def predict_async(
        self, request: apis.PredictRequest, deadline_s: float | None = None,
        criticality: str | None = None, int8_wire: bool = False,
        input_crc: str | None = None,
    ) -> apis.PredictResponse:
        """Predict for coroutine servers: identical semantics, awaits the
        batch instead of blocking a handler thread on it."""
        self._refuse_if_draining()
        deadline_t = self._clock_deadline(deadline_s)
        servable, arrays, out_names, fetch_keys = self._predict_prepare(
            request, criticality, input_crc=input_crc
        )
        casc = self.cascade
        if casc is not None and casc.eligible(
            servable, fetch_keys, next(iter(arrays.values())).shape[0]
        ):
            with request_trace.span("predict.execute"):
                outputs = await casc.run_async(
                    self, servable, arrays, fetch_keys, deadline_t,
                    criticality,
                )
            out_names = [*out_names, cascade_mod.STAGE_OUTPUT]
        else:
            with request_trace.span("predict.execute"):
                outputs = await self._run_async(
                    servable, arrays, output_keys=fetch_keys,
                    deadline_s=self._budget_left(deadline_t),
                    criticality=criticality,
                )
        resp = self._predict_finish(
            request, servable, out_names, outputs, int8_wire=int8_wire
        )
        self._log_request("predict", request)
        return resp

    def _check_produced(self, out_names, outputs) -> None:
        produced = [k for k in out_names if k in outputs]
        if len(produced) != len(out_names):
            # Signature promised tensors the model never produced — a servable
            # configuration bug, not a client error.
            raise ServiceError(
                "INTERNAL",
                f"model produced {sorted(outputs)} but signature declares "
                f"{out_names}",
            )

    @staticmethod
    def _mirror_content(request: apis.PredictRequest) -> bool:
        """Mirror the client's tensor encoding: a client that sent
        repeated fields (the grpc-java builder style, DCNClient.java:
        98-108) reads outputs via getFloatValList(), which is EMPTY if
        we reply with tensor_content — TF-Serving itself replies
        AsProtoField-style. Clients that sent tensor_content get the
        zero-copy fast path back.
        upb map iteration materializes each TensorProto wrapper, which
        is measurably slow at 500 QPS (round-3 profile: ~50 us/call);
        iterating keys and probing one field is several times cheaper,
        and any() still short-circuits on the first content-carrying
        input either way."""
        return any(
            request.inputs[name].tensor_content for name in request.inputs
        )

    def _encode_outputs(
        self, request, servable: Servable, out_names, outputs, dest,
        mirror_content: bool,
    ) -> None:
        """The ONE per-tensor response-encode loop, shared by unary
        responses and stream chunks (their wire encodings must never
        drift): the half-precision wire-dtype leak guard (custom run_fns
        returning the compact transport encoding widen back to the
        signature's DT_FLOAT; genuinely half-precision signatures pass
        through untouched), the client-encoding mirror, and the optional
        per-thread encode arena. `dest` is the response's outputs map."""
        half = (
            codec.dtype_to_numpy(fw.DataType.DT_BFLOAT16),
            np.dtype(np.float16),
        )
        sig_dtypes = None  # built lazily: the leak guard almost never
        # fires (the batcher completer already widened), and this encode
        # path is microbenchmark-hot (~50 us/call at 500 QPS).
        arena = self._arena()
        for name in out_names:
            arr = outputs[name]
            if arr.dtype in half:
                if sig_dtypes is None:
                    sig_dtypes = {
                        s.name: s.dtype
                        for s in servable.signature(
                            request.model_spec.signature_name
                        ).outputs
                    }
                if sig_dtypes.get(name) == fw.DataType.DT_FLOAT:
                    arr = (
                        arena.widen_f32(arr) if arena is not None
                        else arr.astype(np.float32)
                    )
            codec.from_ndarray(
                arr,
                use_tensor_content=mirror_content,
                out=dest[name],
                arena=arena,
            )

    def _predict_finish(
        self, request: apis.PredictRequest, servable: Servable, out_names,
        outputs, int8_wire: bool = False,
    ) -> apis.PredictResponse:
        self._check_produced(out_names, outputs)
        with request_trace.span("predict.encode"):
            resp = apis.PredictResponse()
            resp.model_spec.CopyFrom(
                self._echo_spec(servable, request.model_spec.signature_name or "serving_default")
            )
            mirror = self._mirror_content(request)
            names = out_names
            score_key = servable.model.score_output
            if (
                int8_wire
                and score_key in out_names
                and getattr(outputs.get(score_key), "dtype", None)
                == np.float32
            ):
                # int8 score response wire (ISSUE 12): the opted-in
                # client receives the score tensor as DT_INT8 plus the
                # (scale, min) sidecar outputs codec.dequantize_response_
                # output inverts — 4x fewer response bytes per score.
                # Non-f32 score outputs (imported-graph dtypes) fall
                # through to the normal encode: the wire must never
                # guess a quantization for a dtype it does not own.
                names = [n for n in out_names if n != score_key]
                q, scale, mn = codec.quantize_scores(outputs[score_key])
                codec.from_ndarray(
                    q, dtype_enum=fw.DataType.DT_INT8,
                    use_tensor_content=mirror, out=resp.outputs[score_key],
                )
                codec.from_ndarray(
                    np.asarray([scale], np.float32), use_tensor_content=mirror,
                    out=resp.outputs[score_key + codec.Q8_WIRE_SCALE_SUFFIX],
                )
                codec.from_ndarray(
                    np.asarray([mn], np.float32), use_tensor_content=mirror,
                    out=resp.outputs[score_key + codec.Q8_WIRE_MIN_SUFFIX],
                )
            self._encode_outputs(
                request, servable, names, outputs, resp.outputs, mirror,
            )
        return resp

    # --------------------------------------------------------- PredictStream

    # Guard against pathological sub-batch explosions: a 32k-candidate
    # request with a 1-candidate chunk override must not mint 32k batcher
    # submits. The effective chunk size is raised until the request yields
    # at most this many sub-batches.
    _STREAM_MAX_CHUNKS = 64

    def _stream_plan(
        self, n: int, chunk: int | None
    ) -> list[tuple[int, int]]:
        """[(offset, count)] sub-batch split of an n-candidate request.
        `chunk` (per-request override, e.g. the x-dts-stream-chunk
        metadata) wins over the configured stream_chunk_candidates; 0 or
        absent on both = one chunk (streaming stays wire-available with
        the behavior change off)."""
        chunk_n = int(chunk) if chunk else int(self.stream_chunk_candidates or 0)
        if chunk_n <= 0 or chunk_n >= n:
            return [(0, n)]
        chunk_n = max(chunk_n, -(-n // self._STREAM_MAX_CHUNKS))
        return [(off, min(chunk_n, n - off)) for off in range(0, n, chunk_n)]

    def _stream_submit(
        self, request, deadline_t, criticality, chunk
    ):
        """Shared front half of both predict_stream flavors: resolve,
        decode, split, and submit EVERY sub-batch up front — the
        sub-batches ride the batcher's k-deep pipeline independently, so
        sub-batch k+1 uploads while k executes and k-1 reads back. Returns
        (servable, out_names, mirror_content, total, {future: (off, n)}).
        A submit failure mid-fan-out cancels the siblings already queued
        before translating."""
        servable, arrays, out_names, fetch_keys = self._predict_prepare(
            request, criticality
        )
        total = next(iter(arrays.values())).shape[0]
        plan = self._stream_plan(total, chunk)
        span = tracing.current_span()
        futs: dict = {}
        # A split stream's sub-batches submit _solo so the coalescer never
        # concatenates them back into the one big batch they were split
        # from; an unsplit request keeps ordinary coalescing semantics.
        solo = len(plan) > 1
        try:
            for off, cnt in plan:
                sub = {k: v[off: off + cnt] for k, v in arrays.items()}
                fut = self.batcher.submit(
                    servable, sub, output_keys=fetch_keys,
                    deadline_s=self._budget_left(deadline_t),
                    span=span, criticality=criticality, _solo=solo,
                )
                futs[fut] = (off, cnt)
        except Exception as e:  # noqa: BLE001 — translator re-raises non-batcher
            for f in futs:
                f.cancel()
            raise self._translate_batcher_error(e, None) from e
        return servable, out_names, self._mirror_content(request), total, futs

    def _encode_stream_chunk(
        self, request, servable, out_names, outputs,
        off: int, cnt: int, total: int, final: bool,
        mirror_content: bool, msg=None,
    ) -> apis.PredictStreamChunk:
        """One sub-batch -> one PredictStreamChunk (PredictResponse encode
        semantics — _encode_outputs is the SHARED per-tensor loop, so the
        streamed and unary wire encodings cannot drift). `msg` reuses one
        chunk message across the stream (the response-arena mode): gRPC
        serializes each yielded message before the generator resumes, so
        Clear+refill after yield is safe."""
        self._check_produced(out_names, outputs)
        with request_trace.span("predict.encode"):
            if msg is None:
                chunk = apis.PredictStreamChunk()
            else:
                chunk = msg
                chunk.Clear()
            chunk.model_spec.CopyFrom(self._echo_spec(
                servable, request.model_spec.signature_name or "serving_default"
            ))
            chunk.offset = int(off)
            chunk.count = int(cnt)
            chunk.total = int(total)
            chunk.final = bool(final)
            self._encode_outputs(
                request, servable, out_names, outputs, chunk.outputs,
                mirror_content,
            )
        return chunk

    def predict_stream(
        self, request: apis.PredictRequest, deadline_s: float | None = None,
        criticality: str | None = None, chunk: int | None = None,
    ):
        """Server-streaming Predict (ISSUE 9): a generator of
        PredictStreamChunk — the request is split into sub-batches that
        ride the batcher pipeline independently, and each chunk is yielded
        the moment its readback completes (possibly OUT OF ORDER; chunks
        carry offset/count for the client's incremental merge), so the
        caller's first scores decouple from the slowest sub-batch. Unary
        Predict semantics otherwise: same resolution/validation/encode
        path, same error taxonomy — a failed sub-batch aborts the stream
        with the translated status after cancelling its siblings. A
        deadline expiring mid-stream cancels the remaining sub-batches
        and aborts DEADLINE_EXCEEDED."""
        import concurrent.futures as cf

        self._refuse_if_draining()
        deadline_t = self._clock_deadline(deadline_s)
        timeout = self._effective_timeout(deadline_s)
        give_up_t = time.perf_counter() + timeout
        servable, out_names, mirror_content, total, futs = (
            self._stream_submit(request, deadline_t, criticality, chunk)
        )
        reuse = apis.PredictStreamChunk() if self.response_arena else None
        pending = set(futs)
        emitted = 0
        try:
            while pending:
                left = give_up_t - time.perf_counter()
                if left <= 0:
                    raise ServiceError(
                        "DEADLINE_EXCEEDED",
                        "deadline expired mid-stream "
                        f"({emitted}/{len(futs)} sub-batches delivered)",
                    )
                done, pending = cf.wait(
                    pending, timeout=left,
                    return_when=cf.FIRST_COMPLETED,
                )
                if not done:
                    continue  # loop re-checks the give-up clock
                for fut in done:
                    try:
                        outputs = fut.result()
                    except Exception as e:  # noqa: BLE001 — translator re-raises
                        raise self._translate_batcher_error(e, fut) from e
                    # A stale-row brownout serve on any sub-batch marks
                    # the WHOLE stream degraded — the same trailer a
                    # whole-request stale serve emits (the generator runs
                    # in the RPC's context, so the contextvar reaches the
                    # transport adapter).
                    self._consume_future_degraded(fut)
                    off, cnt = futs[fut]
                    emitted += 1
                    yield self._encode_stream_chunk(
                        request, servable, out_names, outputs,
                        off, cnt, total, final=emitted == len(futs),
                        mirror_content=mirror_content, msg=reuse,
                    )
        except BaseException:
            # Mid-stream failure/deadline/disconnect: withdraw every
            # sub-batch still queued so abandoned work never dispatches.
            for f in pending:
                f.cancel()
            raise
        self._log_request("predict", request)

    async def predict_stream_async(
        self, request: apis.PredictRequest, deadline_s: float | None = None,
        criticality: str | None = None, chunk: int | None = None,
    ):
        """predict_stream for coroutine servers: an async generator that
        awaits sub-batch completions instead of blocking an RPC handler
        thread between chunks."""
        import asyncio

        self._refuse_if_draining()
        deadline_t = self._clock_deadline(deadline_s)
        timeout = self._effective_timeout(deadline_s)
        give_up_t = time.perf_counter() + timeout
        servable, out_names, mirror_content, total, futs = (
            self._stream_submit(request, deadline_t, criticality, chunk)
        )
        reuse = apis.PredictStreamChunk() if self.response_arena else None
        wrapped = {asyncio.wrap_future(f): f for f in futs}
        pending = set(wrapped)
        emitted = 0
        try:
            while pending:
                left = give_up_t - time.perf_counter()
                if left <= 0:
                    raise ServiceError(
                        "DEADLINE_EXCEEDED",
                        "deadline expired mid-stream "
                        f"({emitted}/{len(futs)} sub-batches delivered)",
                    )
                done, pending = await asyncio.wait(
                    pending, timeout=left,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    continue  # loop re-checks the give-up clock
                for task in done:
                    try:
                        outputs = task.result()
                    except Exception as e:  # noqa: BLE001 — translator re-raises
                        raise self._translate_batcher_error(
                            e, wrapped[task]
                        ) from e
                    # Stale-row marker forwarding, as in the sync stream.
                    self._consume_future_degraded(wrapped[task])
                    off, cnt = futs[wrapped[task]]
                    emitted += 1
                    yield self._encode_stream_chunk(
                        request, servable, out_names, outputs,
                        off, cnt, total, final=emitted == len(futs),
                        mirror_content=mirror_content, msg=reuse,
                    )
        except BaseException:
            for task in pending:
                task.cancel()
            for f in wrapped.values():
                if not f.done():
                    f.cancel()
            raise
        self._log_request("predict", request)

    # ----------------------------------------------------- Classify / Regress

    def _examples_prepare(self, request, criticality: str | None = None):
        """Shared front half of Classify/Regress: resolution + Example
        decode. Returns (servable, arrays)."""
        servable, _ = self._resolve(request.model_spec, criticality)
        try:
            arrays = decode_input(
                request.input, servable.model.config.num_fields,
                arena=self._arena(),
            )
        except ExampleDecodeError as e:
            raise ServiceError("INVALID_ARGUMENT", str(e)) from e
        return servable, arrays

    def _run_examples(
        self, request, deadline_s: float | None = None,
        criticality: str | None = None,
    ):
        deadline_t = self._clock_deadline(deadline_s)
        servable, arrays = self._examples_prepare(request, criticality)
        outputs = self._run(
            servable, arrays, output_keys=("prediction_node",),
            deadline_s=self._budget_left(deadline_t),
            criticality=criticality,
        )
        return servable, outputs

    async def _run_examples_async(
        self, request, deadline_s: float | None = None,
        criticality: str | None = None,
    ):
        """_run_examples for coroutine servers (the REST gateway's
        :classify/:regress routes ride the same event loop as :predict)."""
        deadline_t = self._clock_deadline(deadline_s)
        servable, arrays = self._examples_prepare(request, criticality)
        outputs = await self._run_async(
            servable, arrays, output_keys=("prediction_node",),
            deadline_s=self._budget_left(deadline_t),
            criticality=criticality,
        )
        return servable, outputs

    def _classify_finish(
        self, request, servable, outputs
    ) -> apis.ClassificationResponse:
        scores = outputs["prediction_node"]
        resp = apis.ClassificationResponse()
        resp.model_spec.CopyFrom(
            self._echo_spec(servable, request.model_spec.signature_name or "classify")
        )
        for p in scores:
            cls = resp.result.classifications.add()
            cls.classes.add(label="0", score=float(1.0 - p))
            cls.classes.add(label="1", score=float(p))
        return resp

    def _classify_impl(
        self, request: apis.ClassificationRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.ClassificationResponse:
        """classify() minus request logging (multi_inference sub-calls ride
        this so a logged MultiInference record is not double-counted as its
        constituent classifications)."""
        servable, outputs = self._run_examples(
            request, deadline_s=deadline_s, criticality=criticality
        )
        return self._classify_finish(request, servable, outputs)

    def classify(
        self, request: apis.ClassificationRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.ClassificationResponse:
        self._refuse_if_draining()
        resp = self._classify_impl(
            request, deadline_s=deadline_s, criticality=criticality
        )
        self._log_request("classify", request)
        return resp

    async def classify_async(
        self, request: apis.ClassificationRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.ClassificationResponse:
        self._refuse_if_draining()
        servable, outputs = await self._run_examples_async(
            request, deadline_s=deadline_s, criticality=criticality
        )
        resp = self._classify_finish(request, servable, outputs)
        self._log_request("classify", request)
        return resp

    def _regress_finish(self, request, servable, outputs) -> apis.RegressionResponse:
        resp = apis.RegressionResponse()
        resp.model_spec.CopyFrom(
            self._echo_spec(servable, request.model_spec.signature_name or "regress")
        )
        for p in outputs["prediction_node"]:
            resp.result.regressions.add(value=float(p))
        return resp

    def _regress_impl(
        self, request: apis.RegressionRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.RegressionResponse:
        servable, outputs = self._run_examples(
            request, deadline_s=deadline_s, criticality=criticality
        )
        return self._regress_finish(request, servable, outputs)

    def regress(
        self, request: apis.RegressionRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.RegressionResponse:
        self._refuse_if_draining()
        resp = self._regress_impl(
            request, deadline_s=deadline_s, criticality=criticality
        )
        self._log_request("regress", request)
        return resp

    async def regress_async(
        self, request: apis.RegressionRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.RegressionResponse:
        self._refuse_if_draining()
        servable, outputs = await self._run_examples_async(
            request, deadline_s=deadline_s, criticality=criticality
        )
        resp = self._regress_finish(request, servable, outputs)
        self._log_request("regress", request)
        return resp

    # --------------------------------------------------------- MultiInference

    def multi_inference(
        self, request: apis.MultiInferenceRequest, deadline_s: float | None = None,
        criticality: str | None = None,
    ) -> apis.MultiInferenceResponse:
        self._refuse_if_draining()
        if not request.tasks:
            raise ServiceError("INVALID_ARGUMENT", "MultiInferenceRequest has no tasks")
        # Sub-calls run sequentially, so each gets the budget REMAINING at
        # its own start — handing every task the full entry-time deadline
        # would let server work extend tasks x deadline past the instant
        # the client gave up.
        deadline_t = self._clock_deadline(deadline_s)

        def remaining() -> float | None:
            left = self._budget_left(deadline_t)
            if left is not None and left <= 0:
                raise ServiceError(
                    "DEADLINE_EXCEEDED",
                    "client deadline expired between MultiInference tasks",
                )
            return left

        resp = apis.MultiInferenceResponse()
        for task in request.tasks:
            method = task.method_name
            if method == "tensorflow/serving/classify":
                sub = apis.ClassificationRequest(model_spec=task.model_spec, input=request.input)
                out = self._classify_impl(
                    sub, deadline_s=remaining(), criticality=criticality
                )
                r = resp.results.add()
                r.model_spec.CopyFrom(out.model_spec)
                r.classification_result.CopyFrom(out.result)
            elif method == "tensorflow/serving/regress":
                sub = apis.RegressionRequest(model_spec=task.model_spec, input=request.input)
                out = self._regress_impl(
                    sub, deadline_s=remaining(), criticality=criticality
                )
                r = resp.results.add()
                r.model_spec.CopyFrom(out.model_spec)
                r.regression_result.CopyFrom(out.result)
            else:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    f"unsupported MultiInference method {method!r} "
                    "(expected tensorflow/serving/classify or .../regress)",
                )
        self._log_request("multi_inference", request)
        return resp

    # ---------------------------------------------------------- ModelService

    def get_model_status(
        self, request: apis.GetModelStatusRequest
    ) -> apis.GetModelStatusResponse:
        """tensorflow.serving.ModelService/GetModelStatus (get_model_status
        .proto upstream): version states for readiness probes. Loaded
        versions are AVAILABLE by construction — the registry flips
        atomically after load+warmup, so the upstream LOADING/UNLOADING
        transients are never externally observable here.

        A model the server is CONFIGURED for (a watcher owns its base_path
        via --model-base-path or --model-config-file) whose first version
        has not landed yet reports state START — TF-Serving-style readiness
        probes poll through the rollout instead of treating the transient
        as an RPC error. NOT_FOUND remains the answer for names this server
        was never told about."""
        name = request.model_spec.name
        if not name:
            raise ServiceError("INVALID_ARGUMENT", "model_spec.name is required")
        loaded = self.registry.models().get(name)
        if not loaded:
            if not self.is_configured(name):
                raise ServiceError("NOT_FOUND", f"model {name!r} not found")
            version, _label = self._version_choice(request.model_spec)
            resp = apis.GetModelStatusResponse()
            st = resp.model_version_status.add()
            st.version = version or 0  # no version directory discovered yet
            st.state = apis.ModelVersionStatus.START
            st.status.error_code = 0
            return resp
        version, label = self._version_choice(request.model_spec)
        if label is not None:
            servable = _wrap_lookup(
                lambda: self.registry.resolve(name, None, label)
            )
            loaded = [servable.version]
        elif version is not None:
            if version not in loaded:
                raise ServiceError(
                    "NOT_FOUND",
                    f"model {name!r} has no version {version}; have {loaded}",
                )
            loaded = [version]
        resp = apis.GetModelStatusResponse()
        for v in sorted(loaded):
            st = resp.model_version_status.add()
            st.version = v
            st.state = apis.ModelVersionStatus.AVAILABLE
            st.status.error_code = 0
        return resp

    def handle_reload_config(
        self, request: apis.ReloadConfigRequest
    ) -> apis.ReloadConfigResponse:
        """tensorflow.serving.ModelService/HandleReloadConfigRequest
        (model_management.proto upstream).

        Two modes, by deployment shape:
        - multi-model (--model-config-file set `model_lifecycle`): the
          FULL upstream semantics — the supplied model_config_list
          REPLACES the served set (new entries start watchers, absent
          entries stop+unload, existing entries get declarative labels).
          An empty list is refused rather than interpreted as "unload
          everything".
        - single-model modes: scoped to the version_labels maps — the
          blue-green flip over the wire. Each named model's supplied map
          is the DECLARATIVE label state (labels absent from it are
          unassigned); a config naming an unserved model is NOT_FOUND
          (model-list lifecycle belongs to the startup artifact flags).
          Validation+application ride one registry lock acquisition
          (replace_label_maps), so a concurrent unload can never leave
          the reload half-applied."""
        cfg = request.config
        if cfg.WhichOneof("config") != "model_config_list":
            raise ServiceError(
                "INVALID_ARGUMENT",
                "only model_config_list reloads are supported "
                "(custom_model_config has no meaning here)",
            )
        if self.model_lifecycle is not None:
            # Multi-model mode: upstream's FULL reload — the supplied list
            # REPLACES the served model set (add/remove watchers,
            # declarative labels on existing models). Same entry
            # validation as startup.
            from ..utils.config import validate_model_config_entries

            try:
                entries = validate_model_config_entries(
                    cfg.model_config_list.config, "reload config"
                )
            except ValueError as e:
                raise ServiceError("INVALID_ARGUMENT", str(e)) from e
            if not entries:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    "refusing an empty model_config_list (it would unload "
                    "every model; unload explicitly per model instead)",
                )
            try:
                self.model_lifecycle.apply(entries)
            except ValueError as e:
                raise ServiceError("INVALID_ARGUMENT", str(e)) from e
            except (ModelNotFoundError, VersionNotFoundError) as e:
                raise ServiceError("FAILED_PRECONDITION", str(e)) from e
            resp = apis.ReloadConfigResponse()
            resp.status.error_code = 0
            return resp
        maps: dict[str, dict[str, int]] = {}
        served = self.registry.models()  # one snapshot for the advisory check
        for mc in cfg.model_config_list.config:
            if not mc.name:
                raise ServiceError("INVALID_ARGUMENT", "model config missing name")
            if mc.base_path or mc.model_platform:
                # A config may RE-STATE the served source (deploy tools
                # replay their full config to flip a label) — but silently
                # ignoring an actual base-path/platform CHANGE would let
                # the config claim one artifact while the server serves
                # another.
                src = self.served_sources.get(mc.name)
                moved = (
                    src is None
                    or (mc.base_path and mc.base_path != src[0])
                    or (mc.model_platform
                        and mc.model_platform not in ("tensorflow", src[1]))
                )
                if moved:
                    raise ServiceError(
                        "FAILED_PRECONDITION",
                        f"model {mc.name!r}: this server was started in "
                        "single-model mode and cannot apply base_path/"
                        "model_platform changes; model-list reloads require "
                        "--model-config-file (a config re-stating the "
                        "CURRENT source is accepted for label retargeting)",
                    )
            if not served.get(mc.name):
                raise ServiceError(
                    "NOT_FOUND",
                    f"model {mc.name!r} is not served here; reload applies "
                    "version_labels to already-served models (model-list "
                    "lifecycle rides the --model-base-path watcher)",
                )
            maps[mc.name] = {label: int(v) for label, v in mc.version_labels.items()}
        try:
            self.registry.replace_label_maps(maps)
        except ValueError as e:
            # e.g. an empty-string label key — a malformed request.
            raise ServiceError("INVALID_ARGUMENT", str(e)) from e
        except (ModelNotFoundError, VersionNotFoundError) as e:
            # Labels may only name loaded versions; a vanished model or
            # version is a precondition failure, applied-nothing.
            raise ServiceError("FAILED_PRECONDITION", str(e)) from e
        resp = apis.ReloadConfigResponse()
        resp.status.error_code = 0
        return resp

    # ------------------------------------------------------- GetModelMetadata

    def get_model_metadata(
        self, request: apis.GetModelMetadataRequest
    ) -> apis.GetModelMetadataResponse:
        fields = list(request.metadata_field) or [SIGNATURE_DEF_FIELD]
        unknown = [f for f in fields if f != SIGNATURE_DEF_FIELD]
        if unknown:
            raise ServiceError(
                "INVALID_ARGUMENT", f"unsupported metadata_field values {unknown}"
            )
        if not request.model_spec.name:
            raise ServiceError("INVALID_ARGUMENT", "model_spec.name is required")
        version, label = self._version_choice(request.model_spec)
        servable = _wrap_lookup(
            lambda: self.registry.resolve(request.model_spec.name, version, label)
        )

        resp = apis.GetModelMetadataResponse()
        resp.model_spec.CopyFrom(self._echo_spec(servable, ""))
        resp.model_spec.ClearField("signature_name")
        sig_map = apis.SignatureDefMap()
        for name, sd in servable.signature_def_map().items():
            sig_map.signature_def[name].CopyFrom(sd)
        resp.metadata[SIGNATURE_DEF_FIELD].Pack(sig_map)
        return resp
