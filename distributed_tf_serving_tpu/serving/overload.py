"""Adaptive overload control + graceful degradation (the closed-loop
replacement for the batcher's fixed queue_capacity_candidates bound).

A static admission limit is mistuned by construction: too small and the
server sheds at partial load; too large and it queues past every client
deadline, burning device time on work nobody is waiting for. "Scaling
TensorFlow to 300 million predictions per second" attributes survivability
at that scale to LOAD-ADAPTIVE serving; this module is that control loop:

- **Self-tuning admission limit.** The batcher feeds every dispatched
  item's queue wait into a sliding window; an AIMD controller compares the
  windowed p99 against `target_queue_wait_ms` on a fixed tick — under
  target the candidate limit grows additively (`increase_candidates`),
  over target it shrinks multiplicatively (`decrease_factor`), clamped to
  [min_limit, max_limit]. Queue wait — not depth — is the controlled
  variable, so the limit lands wherever THIS host's drain rate puts it.
- **Deadline-aware enqueue refusal.** The batcher also feeds per-batch
  service time; the EWMA per-candidate estimate prices the current
  backlog, and a request whose remaining deadline budget is already
  smaller than the estimated queue wait is refused at submit — doomed
  work is never queued, so it can never delay live work behind it.
- **Criticality lanes.** Every request carries a criticality (client
  metadata `x-dts-criticality`, default "default"); each lane sees a
  FRACTION of the limit, so sheddable traffic is refused first as backlog
  builds and warmup/probe traffic is always the first to go. Under SHED
  (and only SHED — brownout must keep admitting rollout warmup, or a
  hot-loaded version gets blacklisted mid-overload), sheddable and probe
  traffic are refused outright.
- **Pressure state machine** NOMINAL -> BROWNOUT -> SHED, advanced by
  consecutive over/under-target ticks. In brownout (and shed) the batcher
  serves STALE score-cache entries within `stale_while_overloaded_s`
  (responses marked degraded via trailing metadata / the X-DTS-Degraded
  header; never re-filled into the cache), so hot-key traffic keeps
  getting answers while the device catches up.
- **Client pushback.** Every refusal carries a `retry-after-ms` hint
  (trailing metadata on RESOURCE_EXHAUSTED; Retry-After on HTTP 429),
  sized from the backlog's estimated drain time. The fan-out client's
  backoff honors it, and its scoreboard records pushback as "busy", not
  "dead" — a shedding backend is biased against (and never hedged into),
  but never ejected.

Deterministic by construction: an injectable clock, no background thread
(the controller ticks opportunistically from the submit path), and a
`pressure` fault site (faults.py) that lets tests force the state machine
into BROWNOUT/SHED without generating real load.

Everything is off by default ([overload] enabled=false); when off the
batcher pays one attribute read per submit — the tracing/faults precedent.
"""

from __future__ import annotations

import contextvars
import dataclasses
import threading
import time
from collections import deque

from .. import faults

# Pressure states, in escalation order.
NOMINAL, BROWNOUT, SHED = "nominal", "brownout", "shed"
_STATE_ORDER = (NOMINAL, BROWNOUT, SHED)

# Criticality lanes, most- to least-important. The client sends the lane in
# gRPC/HTTP metadata (CRITICALITY_KEY); warmup/probe traffic is assigned
# PROBE by the batcher itself.
CRITICAL, DEFAULT, SHEDDABLE, PROBE = "critical", "default", "sheddable", "probe"
LANES = (CRITICAL, DEFAULT, SHEDDABLE, PROBE)

# Fraction of the current limit each lane may fill: sheddable traffic hits
# its ceiling first as backlog builds, probe/warmup first of all. A single
# request on an EMPTY queue always admits regardless (warming the largest
# bucket must never be refused by its own lane fraction on an idle server).
_LANE_FRACTION = {CRITICAL: 1.0, DEFAULT: 0.9, SHEDDABLE: 0.7, PROBE: 0.5}

# Wire metadata keys. The client package repeats these as literals (it must
# stay importable without the serving package's jax dependency).
CRITICALITY_KEY = "x-dts-criticality"
RETRY_AFTER_KEY = "retry-after-ms"
DEGRADED_KEY = "x-dts-degraded"


def normalize_criticality(value) -> str:
    """Map a wire criticality value onto a known lane; unknown/absent is
    DEFAULT (a typo'd criticality must not grant CRITICAL treatment — nor
    accidentally mark traffic sheddable)."""
    v = str(value or "").strip().lower()
    return v if v in LANES else DEFAULT


# --------------------------------------------------------- degraded marker
#
# The brownout stale-serve happens deep inside batcher.submit, but the
# "this response is degraded" marker must reach the TRANSPORT (trailing
# metadata / HTTP header). submit runs synchronously inside the RPC's
# thread (sync server) or coroutine task (aio/REST), so a contextvar
# carries the flag out without threading a return channel through every
# layer. Transports clear at entry and consume after success.

_DEGRADED: contextvars.ContextVar = contextvars.ContextVar(
    "dts_tpu_degraded", default=None
)

_ACTIVE = False  # fast-path gate: one bool read when no controller exists


def active() -> bool:
    return _ACTIVE


def _set_active(value: bool) -> None:
    global _ACTIVE
    _ACTIVE = value


def deactivate() -> None:
    """Clear the fast-path gate after a temporarily-armed controller is
    discarded (benches/tests that attach one for a phase, then detach).
    Server processes never call this — an armed stack stays armed for its
    lifetime; without the clear, every later request in the process keeps
    paying the metadata scans the gate exists to skip."""
    _set_active(False)


def mark_degraded(kind: str = "stale") -> None:
    _DEGRADED.set(kind)


def consume_degraded():
    """Read-and-clear the current request's degraded marker (None when the
    response is a full-fidelity answer)."""
    value = _DEGRADED.get()
    if value is not None:
        _DEGRADED.set(None)
    return value


# ------------------------------------------------------------- controller


@dataclasses.dataclass(frozen=True)
class Decision:
    """One admit() outcome. When refused, `reason` is "shed" (capacity /
    lane pressure) or "doomed" (estimated wait exceeds the request's
    remaining deadline budget) and `retry_after_ms` is the pushback hint
    the RPC layer forwards in trailing metadata."""

    admitted: bool
    reason: str | None = None
    message: str = ""
    retry_after_ms: int | None = None


class AdmissionController:
    """The closed loop: windowed queue-wait p99 vs. target drives an AIMD
    candidate limit; EWMA per-candidate service time prices the backlog
    for doomed-work refusal and retry-after hints; consecutive over/under
    ticks drive the NOMINAL/BROWNOUT/SHED pressure state.

    Thread-safe; everything rides one small lock (admission is already
    serialized under the batcher's condition variable, and the feed paths
    are the batcher's own threads). No background thread: `admit` and the
    note_* feeds tick the controller when `adjust_interval_s` elapsed, so
    a fake clock makes every trajectory deterministic under test.
    """

    # Bounded sample memory: at most this many queue-wait samples are held
    # regardless of traffic rate (~100 KB; the p99 of a 4096-sample window
    # is plenty stable for a control loop).
    MAX_WAIT_SAMPLES = 4096

    def __init__(self, cfg, clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        # Limits resolve in bind() (the batcher knows the bucket ladder);
        # until then a conservative default keeps a detached controller
        # (unit tests) usable.
        self._min = max(int(getattr(cfg, "min_limit_candidates", 0)) or 1, 1)
        self._max = max(int(getattr(cfg, "max_limit_candidates", 0)) or self._min, self._min)
        self.limit = self._max
        self._bound = False
        self._ewma_per_cand_s: float | None = None
        # Window entries are (t, wait_s, over_target); the running
        # over-target count makes the tick's "is p99 over target?" test
        # O(1) — admit() runs under the batcher's condition variable, so
        # the tick must never sort the window there (the exact numeric
        # p99 is only computed lazily, in snapshot(), for telemetry).
        self._waits: deque = deque()
        self._over_count = 0
        self._last_tick = clock()
        self._state = NOMINAL
        self._over = 0
        self._under = 0
        # Telemetry (names are the acceptance-criteria vocabulary).
        self.queue_wait_p99_ms = 0.0
        self.admitted = 0
        self.sheds = 0
        self.sheds_by_lane = {lane: 0 for lane in LANES}
        self.doomed_refusals = 0
        self.brownout_serves = 0
        self.limit_increases = 0
        self.limit_decreases = 0
        self.state_changes = 0
        self.ticks = 0
        _set_active(True)

    # -------------------------------------------------------------- wiring

    def bind(self, largest_bucket: int, queue_capacity: int) -> None:
        """Resolve the auto (0) limit knobs against the batcher's actual
        geometry: min defaults to one largest bucket (a full-size request
        must always admit on an idle queue), max to the static capacity
        the controller replaces (never looser than the operator's old
        bound), and the limit STARTS at max — the controller only ratchets
        down from observed queue wait, so an unloaded server behaves
        exactly like the static bound until pressure teaches it better."""
        with self._lock:
            cfg = self.cfg
            self._min = int(getattr(cfg, "min_limit_candidates", 0)) or largest_bucket
            self._max = int(getattr(cfg, "max_limit_candidates", 0)) or max(
                queue_capacity, self._min
            )
            self._max = max(self._max, self._min)
            self.limit = self._max
            self._bound = True

    @property
    def min_limit(self) -> int:
        return self._min

    @property
    def max_limit(self) -> int:
        return self._max

    # --------------------------------------------------------------- feeds

    def note_queue_wait(self, wait_s: float) -> None:
        with self._lock:
            self._note_wait_locked(wait_s)

    def note_queue_waits(self, waits_s) -> None:
        """Batch form: one lock acquisition for a whole dispatch group."""
        with self._lock:
            for w in waits_s:
                self._note_wait_locked(w)

    def _note_wait_locked(self, wait_s: float) -> None:
        wait_s = float(wait_s)
        over = wait_s * 1e3 > float(self.cfg.target_queue_wait_ms)
        self._waits.append((self._clock(), wait_s, over))
        if over:
            self._over_count += 1
        while len(self._waits) > self.MAX_WAIT_SAMPLES:
            self._pop_oldest_locked()

    def _pop_oldest_locked(self) -> None:
        _, _, was_over = self._waits.popleft()
        if was_over:
            self._over_count -= 1

    def _prune_window_locked(self, now: float) -> None:
        horizon = now - float(getattr(self.cfg, "queue_wait_window_s", 10.0))
        while self._waits and self._waits[0][0] < horizon:
            self._pop_oldest_locked()

    def note_batch(self, candidates: int, service_s: float) -> None:
        """One completed batch's device-stage wall time (dispatch start ->
        readback done). Feeds the EWMA per-candidate service time that
        prices backlogs; overlapped pipeline batches make it a slightly
        conservative (high) estimate, which errs toward refusing doomed
        work early rather than queueing it."""
        if candidates <= 0 or service_s < 0:
            return
        per = service_s / candidates
        alpha = float(getattr(self.cfg, "service_ewma_alpha", 0.2))
        with self._lock:
            self._ewma_per_cand_s = (
                per
                if self._ewma_per_cand_s is None
                else (1 - alpha) * self._ewma_per_cand_s + alpha * per
            )
            self._maybe_tick_locked(self._clock())

    def note_brownout_serve(self) -> None:
        with self._lock:
            self.brownout_serves += 1

    # ---------------------------------------------------------- controller

    def _queue_wait_p99_locked(self, now: float) -> float:
        """Exact windowed p99 — telemetry only (snapshot()). The tick's
        control decision uses the O(1) over-target count instead; this
        sort must stay off the admission path, which runs under the
        batcher's condition variable."""
        self._prune_window_locked(now)
        if not self._waits:
            return 0.0
        vals = sorted(w for _, w, _ in self._waits)
        return vals[min(int(len(vals) * 0.99), len(vals) - 1)]

    def _enter_locked(self, state: str) -> None:
        if state != self._state:
            self._state = state
            self.state_changes += 1
            # Each transition re-earns the next one: shed_after_intervals /
            # recover_after_intervals count ticks AFTER the last change
            # (the documented "further ticks" semantics), not cumulatively
            # from NOMINAL.
            self._over = self._under = 0

    def _maybe_tick_locked(self, now: float) -> None:
        cfg = self.cfg
        if now - self._last_tick < float(getattr(cfg, "adjust_interval_s", 0.5)):
            return
        self._last_tick = now
        self.ticks += 1
        # Deterministic test hook: a `pressure` fault rule whose code names
        # a state ("BROWNOUT"/"SHED"/"NOMINAL") pins the machine there for
        # as long as the rule fires — no real load required.
        if faults.active():
            try:
                faults.fire("pressure")
            except faults.InjectedFaultError as e:
                forced = e.code_name.lower()
                if forced in _STATE_ORDER:
                    self._enter_locked(forced)
                    self._over = self._under = 0
                    return
        # "p99 over target" without sorting: the windowed p99 exceeds the
        # target iff at least (n - p99_index) samples individually do, and
        # the over-target count is maintained incrementally — the tick is
        # O(1) beyond amortized window pruning, cheap enough to run under
        # the batcher's condition variable (admit()'s caller).
        self._prune_window_locked(now)
        n = len(self._waits)
        over = False
        if n:
            over = self._over_count >= n - min(int(n * 0.99), n - 1)
        if over:
            self._over += 1
            self._under = 0
            shrunk = max(int(self.limit * float(cfg.decrease_factor)), self._min)
            if shrunk < self.limit:
                self.limit = shrunk
                self.limit_decreases += 1
        else:
            self._under += 1
            self._over = 0
            if self.limit < self._max:
                self.limit = min(
                    self.limit + int(cfg.increase_candidates), self._max
                )
                self.limit_increases += 1
        if self._state == NOMINAL:
            if self._over >= int(cfg.brownout_after_intervals):
                self._enter_locked(BROWNOUT)
        elif self._state == BROWNOUT:
            if self._over >= int(cfg.shed_after_intervals):
                self._enter_locked(SHED)
        if self._state != NOMINAL and self._under >= int(
            cfg.recover_after_intervals
        ):
            self._enter_locked(
                _STATE_ORDER[_STATE_ORDER.index(self._state) - 1]
            )

    def _retry_after_ms_locked(self, backlog: int) -> int:
        """Pushback hint: roughly half the backlog's estimated drain time —
        retries arriving as the queue crosses back under the limit, not
        after it fully empties (which would waste the freed capacity)."""
        per = self._ewma_per_cand_s if self._ewma_per_cand_s is not None else 1e-4
        ms = backlog * per * 1e3 / 2
        floor = int(getattr(self.cfg, "retry_after_floor_ms", 25))
        cap = int(getattr(self.cfg, "retry_after_cap_ms", 2000))
        return int(min(max(ms, floor), cap))

    # ----------------------------------------------------------- admission

    def admit(
        self,
        n: int,
        backlog: int,
        lane: str = DEFAULT,
        deadline_s: float | None = None,
    ) -> Decision:
        """Admission verdict for `n` candidates against `backlog` already
        queued+staged. Called by the batcher under its own lock (the
        reservation the caller makes on admit keeps concurrent submits
        from overshooting, exactly like the static bound it replaces)."""
        lane = lane if lane in _LANE_FRACTION else DEFAULT
        with self._lock:
            self._maybe_tick_locked(self._clock())
            state = self._state
            # Only full SHED refuses probe/sheddable outright. Brownout
            # must NOT: version-rollout warmup rides the probe lane
            # (warmup_via_queue), and a server sitting in brownout for
            # minutes would fail every hot-load attempt until the watcher
            # blacklists the new version — during exactly the overload a
            # rollout may be trying to fix. In brownout, probe traffic is
            # instead squeezed by its (lowest) lane fraction below.
            if state == SHED and lane in (PROBE, SHEDDABLE):
                return self._refuse_locked(
                    lane, "shed", self._retry_after_ms_locked(backlog),
                    f"{lane} traffic refused under shed pressure",
                )
            # Doomed-work refusal: if the backlog's estimated wait already
            # exceeds the request's remaining budget, queueing it only
            # manufactures a future DEADLINE_EXCEEDED that still costs a
            # dispatch slot to shed.
            if (
                bool(getattr(self.cfg, "deadline_refusal", True))
                and deadline_s is not None
                and backlog > 0
                and self._ewma_per_cand_s is not None
            ):
                est = backlog * self._ewma_per_cand_s
                if est > deadline_s:
                    self.doomed_refusals += 1
                    return self._refuse_locked(
                        lane, "doomed", self._retry_after_ms_locked(backlog),
                        f"estimated queue wait {est * 1e3:.0f}ms exceeds "
                        f"remaining deadline {deadline_s * 1e3:.0f}ms "
                        f"(backlog {backlog} candidates); refusing doomed "
                        "work at enqueue",
                    )
            # Lane-capped capacity. A request landing on an EMPTY queue is
            # always admitted: the lane fraction exists to decide who eats
            # the backlog, not to refuse work an idle device could start
            # immediately.
            cap = int(self.limit * _LANE_FRACTION[lane])
            if backlog > 0 and backlog + n > cap:
                return self._refuse_locked(
                    lane, "shed", self._retry_after_ms_locked(backlog),
                    f"queue holds {backlog} candidates; admitting {n} more "
                    f"would exceed the {lane}-lane limit {cap} "
                    f"(adaptive limit {self.limit})",
                )
            self.admitted += 1
            return Decision(admitted=True)

    def _refuse_locked(
        self, lane: str, reason: str, hint: int, message: str
    ) -> Decision:
        self.sheds += 1
        self.sheds_by_lane[lane] += 1
        return Decision(
            admitted=False, reason=reason, retry_after_ms=hint, message=message
        )

    # ----------------------------------------------------------- observers

    def state(self) -> str:
        with self._lock:
            self._maybe_tick_locked(self._clock())
            return self._state

    def stale_serve_active(self) -> bool:
        """True when brownout stale-serving applies: pressure is past
        NOMINAL and a stale window is configured. Called per submit when a
        cache is armed — INCLUDING on fresh cache hits, which makes this
        the tick that lets pressure recover under cache-hit-only traffic
        (hits bypass admit(), and an idle device dispatches no batches, so
        nothing else would ever advance the state machine: without this
        tick a controller left in BROWNOUT would keep answering expired
        hot keys stale+degraded for the whole stale window while the
        device sits idle). Fast path is a lock-free interval check; the
        tick itself is O(1)."""
        now = self._clock()
        if now - self._last_tick >= float(
            getattr(self.cfg, "adjust_interval_s", 0.5)
        ):
            with self._lock:
                self._maybe_tick_locked(now)
        return (
            self._state != NOMINAL
            and float(getattr(self.cfg, "stale_while_overloaded_s", 0.0)) > 0
        )

    @property
    def stale_window_s(self) -> float:
        return float(getattr(self.cfg, "stale_while_overloaded_s", 0.0))

    def snapshot(self) -> dict:
        with self._lock:
            # Tick + recompute the exact p99 here so /monitoring and the
            # Prometheus series never report a pressure state or p99 that
            # went stale because no admission-path traffic is ticking the
            # controller (idle server, cache-hit-only load).
            now = self._clock()
            self._maybe_tick_locked(now)
            self.queue_wait_p99_ms = self._queue_wait_p99_locked(now) * 1e3
            return {
                "enabled": True,
                "state": self._state,
                "limit": self.limit,
                "min_limit": self._min,
                "max_limit": self._max,
                "target_queue_wait_ms": float(self.cfg.target_queue_wait_ms),
                "queue_wait_p99_ms": round(self.queue_wait_p99_ms, 3),
                "ewma_service_us_per_candidate": (
                    round(self._ewma_per_cand_s * 1e6, 3)
                    if self._ewma_per_cand_s is not None
                    else None
                ),
                "admitted": self.admitted,
                "sheds": self.sheds,
                "sheds_by_lane": dict(self.sheds_by_lane),
                "doomed_refusals": self.doomed_refusals,
                "brownout_serves": self.brownout_serves,
                "limit_increases": self.limit_increases,
                "limit_decreases": self.limit_decreases,
                "state_changes": self.state_changes,
                "ticks": self.ticks,
            }
