"""Continuous-freshness lifecycle plane — online fine-tune publish, canary
admission, drift/AUC auto-rollback (ISSUE 8).

CTR models go stale in hours. Every mechanical piece already exists in the
stack — trainer + checkpoint (train/), atomic export + version allocation
(interop/export.py publish_version), hot-swap mid-traffic (serving/
version_watcher.py), the probe criticality lane (overload plane), and the
quality plane's live version-pair PSI/JS drift + label-feedback AUC
(serving/quality.py) — but nothing closed the loop. This module is the
ACTUATOR the ROADMAP's item 5 names: the TF-Serving paper's canonical
lifecycle story (train -> publish -> canary -> promote | rollback), run by
the serving process itself.

Three cooperating parts, one controller object:

- **Fine-tune publisher**: when `[lifecycle] fine_tune_interval_s > 0`
  and the controller sits IDLE, the background loop fine-tunes the
  CURRENT stable servable on fresh labeled rows (train/publisher.py — the
  synthetic stream by default, any `data_fn` in embedded use) and lands
  the result in the watched base dir as the next numeric version via the
  tmp-dir + rename commit protocol (interop/export.py publish_version) —
  the version watcher's readiness probe can never observe a half-written
  dir. Soaks/benches publish externally through the same helper; the
  controller treats any new on-disk version identically.

- **Canary admission**: when the watcher hot-loads a NEWER version next
  to the stable one, the controller enters CANARY and takes over DEFAULT
  version resolution (requests that pin a version or label are never
  touched): probe-lane traffic (x-dts-criticality: probe — the lane
  warmup already rides) routes to the canary immediately, then a
  time-driven ramp sends a deterministic, configurable fraction of
  default-lane traffic after it. Routed requests execute under their
  version's own servable, so the quality plane's per-(model, version)
  sketches — and its version_pair drift — see real paired traffic with
  no extra plumbing.

- **Auto-rollback / promotion**: a tick loop (injectable clock; the
  background thread is OPTIONAL — tests and embedded callers drive
  `tick()` directly) reads the quality plane's pair drift (PSI/JS between
  the stable and canary windowed score distributions) and per-version
  label-feedback AUC. A canary that regresses past `rollback_psi` or
  loses more than `rollback_auc_drop` AUC is rolled back: canary routing
  drains instantly, the version watcher retires the version from the
  registry mid-traffic AND blacklists it so the next reconcile pass
  cannot reload it from disk. A canary that holds within thresholds
  through the full ramp for `promote_after_s` is promoted: routing
  overrides drop away and the registry's latest-version default serves
  it to everyone.

State machine: IDLE -> CANARY -> PROMOTING -> IDLE, with
CANARY -> ROLLED_BACK -> IDLE on regression. Surfaces: GET /lifecyclez,
a `lifecycle` block in /monitoring, and dts_tpu_lifecycle_* Prometheus
series. Off by default ([lifecycle] enabled=false / --lifecycle); when
off the service pays ONE attribute read per resolution (the
tracing/cache/overload precedent).

jax-optional by design: routing, ticks, and every surface run without a
device in sight; only the optional fine-tune publisher (train/publisher
.py, imported lazily) touches jax.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque

from . import overload as overload_mod

log = logging.getLogger("dts_tpu.lifecycle")

# States (string values are the wire/JSON encoding, lowercase for labels).
IDLE = "idle"
CANARY = "canary"
PROMOTING = "promoting"
ROLLED_BACK = "rolled_back"
STATES = (IDLE, CANARY, PROMOTING, ROLLED_BACK)

# Fast-path gate mirroring overload.active(): the transport adapters scan
# criticality metadata only while SOME plane that consumes it is armed.
_ACTIVE = False


def active() -> bool:
    return _ACTIVE


def _activate() -> None:
    global _ACTIVE
    _ACTIVE = True


def deactivate() -> None:
    """Drop the module-level fast-path gate (bench/test teardown)."""
    global _ACTIVE
    _ACTIVE = False


class LifecycleController:
    """The freshness actuator: canary routing + promote/rollback ticks +
    the optional fine-tune publisher cadence.

    Collaborators are injected — `registry` (which versions are live),
    `watcher` (blacklist/pin/retire; None tolerated for embedded use,
    rollback then unloads through the registry directly), `quality` (the
    drift/AUC signal; None tolerated — promotion then rests on the dwell
    alone and rollback never fires, the bench's mechanics-cost mode) —
    so the state machine is testable with a fake clock and no threads.
    `publisher()` overrides the fine-tune publish step (soaks publish
    poisoned canaries through it).
    """

    def __init__(
        self,
        config,
        *,
        registry,
        model_name: str,
        watcher=None,
        quality=None,
        publisher=None,
        clock=time.monotonic,
    ):
        self.config = config
        self.registry = registry
        self.model = model_name
        self.watcher = watcher
        self.quality = quality
        self.publisher = publisher
        keep = getattr(getattr(watcher, "config", None), "keep_versions", 2)
        if keep < 2:
            # With keep_versions=1 the watcher's OWN poll pass retires
            # the stable version the instant it loads the canary —
            # before this controller's next tick can pin it — leaving no
            # rollback target and silently adopting the canary with no
            # judgment. Refuse at construction, not mid-rollout.
            raise ValueError(
                "the lifecycle plane needs keep_versions >= 2 on its "
                f"version watcher (got {keep}): stable and canary must "
                "be loadable side by side or there is no rollback target"
            )
        self._clock = clock
        self._lock = threading.Lock()
        # Tick serialization: ticks fire opportunistically from request
        # threads (route) AND from the optional background thread; two
        # concurrent evaluations of the same CANARY state would double-
        # fire its transition (two rollbacks counted, retire raced).
        # Non-blocking: a racer skips — the in-flight tick covers it.
        self._tick_mutex = threading.Lock()
        self._state = IDLE
        self._state_since = clock()
        # Recovery-plane interplay (ISSUE 11): while the serving replica
        # is quarantined/rebuilding its executor, canary ticks pause —
        # judging (or ramping) a canary against a dying device would
        # read device failure as model regression. Plain bool, flipped
        # by pause()/resume(); tick() no-ops while set.
        self._paused = False
        self._stable: int | None = None
        self._canary: int | None = None
        self._fraction = 0.0
        # Fleet-coordinated rollout (ISSUE 17): when the fleet plane sets
        # a fleet-global ramp fraction, it overrides the local ramp
        # schedule — every replica serves the SAME canary share, decided
        # once by the rollout coordinator. None = local schedule.
        self._fleet_fraction: float | None = None
        self._route_seq = 0
        self._next_tick = -math.inf
        # When the ramp first reached max_fraction (None below it): the
        # promote dwell is measured AT the ceiling, as the config knob
        # documents — ramp time is not full-share evidence.
        self._full_since: float | None = None
        # Counters (all monotonic; Prometheus reads them off snapshot()).
        self.ticks = 0
        self.promotes = 0
        self.rollbacks = 0
        self.publishes = 0
        self.publish_failures = 0
        self.routed_canary = 0
        self.routed_stable = 0
        self.routed_probe = 0
        self._last_publish_t = clock()
        self._last_judgment: dict | None = None
        self._last_rollback: dict | None = None
        self._promoted_version: int | None = None
        self._rolled_back_version: int | None = None
        self._events: deque[dict] = deque(
            maxlen=max(int(getattr(config, "history_events", 64)), 8)
        )
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        _activate()  # transports now scan the criticality lane for route()

    # ------------------------------------------------------------- routing

    def route(self, criticality: str | None = None) -> int | None:
        """Version override for one DEFAULT-resolution request of this
        controller's model (requests pinning a version or label never
        reach here). None = no override, serve the registry's latest.

        Probe-lane traffic goes to the canary from the moment CANARY is
        entered (the warmup lane is exactly the traffic a fresh version
        should absorb first); default-lane traffic follows a deterministic
        counter ramp — request k routes canary iff floor(k*f) advances,
        so a fraction f sends exactly that share with no RNG to seed.
        Ticks ride along opportunistically (one float compare per call),
        so an armed controller makes progress under pure traffic with no
        background thread."""
        now = self._clock()
        if now >= self._next_tick:
            self.tick(now)
        with self._lock:
            if self._state != CANARY:
                return None
            canary, stable = self._canary, self._stable
            lane = overload_mod.normalize_criticality(criticality)
            if lane == overload_mod.PROBE:
                self.routed_probe += 1
                self.routed_canary += 1
                return canary
            frac = self._fraction
            if frac >= 1.0:
                self.routed_canary += 1
                return canary
            if frac > 0.0:
                self._route_seq += 1
                k = self._route_seq
                if math.floor(k * frac) > math.floor((k - 1) * frac):
                    self.routed_canary += 1
                    return canary
            self.routed_stable += 1
            return stable

    # --------------------------------------------------------------- ticks

    def pause(self) -> None:
        """Suspend canary ticks (recovery quarantine): routing keeps its
        current answer but the state machine stops advancing — no ramp
        steps, no promote dwell credit accrual source, and critically no
        rollback judged against quarantine-corrupted evidence."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    @property
    def paused(self) -> bool:
        return self._paused

    def tick(self, now: float | None = None) -> None:
        """One control-loop pass. Reentrancy-safe; quality reads happen
        OUTSIDE the controller lock (the monitor locks itself), then the
        transition re-checks state before applying."""
        if self._paused:
            return  # recovery quarantine in progress (see pause())
        now = self._clock() if now is None else now
        if not self._tick_mutex.acquire(blocking=False):
            return  # a concurrent tick is already evaluating this state
        try:
            with self._lock:
                self.ticks += 1
                self._next_tick = now + max(self.config.tick_interval_s, 0.05)
                state = self._state
            if state == IDLE:
                self._tick_idle(now)
            elif state == CANARY:
                self._tick_canary(now)
            elif state == PROMOTING:
                self._enter(IDLE, now, event="settled")
            elif state == ROLLED_BACK:
                if now - self._state_since >= self.config.rollback_hold_s:
                    self._enter(IDLE, now, event="rollback_hold_elapsed")
        finally:
            self._tick_mutex.release()

    def _versions(self) -> list[int]:
        return sorted(self.registry.models().get(self.model, ()))

    def _enter(self, state: str, now: float, event: str, **detail) -> None:
        with self._lock:
            self._state = state
            self._state_since = now
            self._events.append({
                "t": round(now, 3),
                "state": state,
                "event": event,
                **detail,
            })
        log.info("lifecycle %s -> %s (%s) %s", self.model, state, event,
                 detail or "")

    def _tick_idle(self, now: float) -> None:
        versions = self._versions()
        if not versions:
            return
        latest = versions[-1]
        with self._lock:
            stable = self._stable
        if stable is None or stable not in versions:
            # Adopt the current latest as stable WITHOUT a canary phase:
            # at controller start (or after an external retire) the
            # serving version is already carrying full traffic — routing
            # it back down to an older version would be a regression, not
            # a canary.
            with self._lock:
                self._stable = latest
                if stable != latest:
                    # Appended under the lock: snapshot() iterates the
                    # deque there, and a concurrent append would raise
                    # "deque mutated during iteration" mid-scrape.
                    self._events.append({
                        "t": round(now, 3), "state": IDLE,
                        "event": "adopted_stable", "version": latest,
                    })
            return
        if latest > stable:
            if self.watcher is not None and self._safe(
                lambda: self.watcher.is_blacklisted(latest), False
            ):
                return  # a blacklisted version must never re-enter canary
            with self._lock:
                self._canary = latest
                self._fraction = 0.0
                self._route_seq = 0
                self._full_since = None
            if self.watcher is not None:
                # Pin the stable version: retention must not retire the
                # rollback target out from under a live canary.
                self._safe(lambda: self.watcher.pin(stable))
            self._enter(CANARY, now, event="canary_started",
                        stable=stable, canary=latest)

    def _tick_canary(self, now: float) -> None:
        with self._lock:
            stable, canary = self._stable, self._canary
            since = self._state_since
        versions = self._versions()
        if canary not in versions:
            # Retired externally (operator, reload-config): drain routing
            # and fall back to IDLE; _tick_idle re-adopts whatever leads.
            self._clear_canary()
            self._enter(IDLE, now, event="canary_vanished", canary=canary)
            return
        if stable not in versions:
            # The rollback target is gone (external unload past the pin):
            # the canary is the only live version — promote by necessity.
            self._promote(now, reason="stable_vanished")
            return
        judgment = self._judge(stable, canary)
        with self._lock:
            self._last_judgment = judgment
        if judgment["verdict"] == "regressed":
            self._rollback(now, judgment)
            return
        cfg = self.config
        elapsed = now - since
        ramp_t = elapsed - cfg.canary_probe_only_s
        if ramp_t < 0:
            frac = 0.0
        else:
            steps = math.floor(ramp_t / max(cfg.canary_step_dwell_s, 1e-9))
            frac = min(
                cfg.canary_initial_fraction + steps * cfg.canary_ramp_step,
                cfg.canary_max_fraction,
            )
        fleet_frac = self._fleet_fraction
        if fleet_frac is not None:
            # Fleet override: the coordinator's fraction wins over the
            # local clock (still capped at the operator's ceiling — the
            # fleet can slow a replica down or catch it up, not push it
            # past its configured max).
            frac = min(max(float(fleet_frac), 0.0), cfg.canary_max_fraction)
        with self._lock:
            if frac != self._fraction:
                self._route_seq = 0  # restart the counter ramp per step
            self._fraction = frac
            if frac >= cfg.canary_max_fraction:
                if self._full_since is None:
                    self._full_since = now
            else:
                self._full_since = None
            full_since = self._full_since
        if (
            full_since is not None
            and now - full_since >= cfg.promote_after_s
            # The dwell is measured AT the ceiling (the knob's documented
            # semantics): ramp time is not full-share evidence. "ok"
            # requires quality evidence; "no_signal" (no quality monitor)
            # promotes on the dwell alone — the documented mechanics
            # mode; "insufficient" never does.
            and judgment["verdict"] in ("ok", "no_signal")
        ):
            self._promote(now, reason="healthy_dwell", judgment=judgment)

    # ----------------------------------------------------------- judgment

    def _judge(self, stable: int, canary: int) -> dict:
        """Read the quality plane's canary-vs-stable evidence. Verdicts:
        'regressed' (roll back now), 'ok' (evidence present and within
        thresholds), 'insufficient' (not enough canary data yet — keep
        ramping, never promote on it). Without a quality monitor the
        verdict is 'no_signal': promotion rests on the dwell alone and
        rollback never fires (document-level trade-off for embedded /
        bench use; the server build refuses to arm this plane without
        [quality])."""
        q = self.quality
        cfg = self.config
        if q is None:
            return {"verdict": "no_signal"}
        out: dict = {"verdict": "insufficient"}
        try:
            canary_scores = q.version_window_count(self.model, canary)
            out["canary_window_scores"] = canary_scores
            pair = q.pair_drift(
                self.model, stable, canary,
                min_count=cfg.min_canary_scores,
                # Decision-grade comparison: coarsened bins, so a small
                # fresh-canary window's sampling noise cannot impersonate
                # a shift (the raw fine-bin PSI stays on /qualityz).
                decision_bins=getattr(cfg, "rollback_compare_bins", 10),
            )
            out["pair"] = pair
            s_auc, s_n = q.version_auc(self.model, stable)
            c_auc, c_n = q.version_auc(self.model, canary)
            out["auc"] = {
                "stable": s_auc, "stable_pairs": s_n,
                "canary": c_auc, "canary_pairs": c_n,
            }
            if pair is not None and pair["psi"] >= cfg.rollback_psi:
                out["verdict"] = "regressed"
                out["reason"] = "psi"
                return out
            if (
                s_auc is not None and c_auc is not None
                and s_n >= cfg.min_auc_pairs and c_n >= cfg.min_auc_pairs
                and s_auc - c_auc >= cfg.rollback_auc_drop
            ):
                out["verdict"] = "regressed"
                out["reason"] = "auc"
                return out
            if pair is not None and canary_scores >= cfg.min_canary_scores:
                out["verdict"] = "ok"
            elif (
                pair is None
                and cfg.canary_max_fraction >= 0.95
                and canary_scores >= cfg.min_canary_scores
                and q.version_window_count(self.model, stable)
                < cfg.min_canary_scores
            ):
                # The STABLE side is starved BY CONSTRUCTION — only at a
                # ~1.0 ramp ceiling, where everything routes to the
                # canary, does the stable window drain with pair evidence
                # UNOBTAINABLE; waiting would wedge the rollout forever,
                # so promotion rests on the dwell + canary volume. At a
                # partial ceiling a starved stable just means low
                # traffic: the verdict stays "insufficient" — promoting
                # without the comparison would skip the one judgment this
                # plane exists to make.
                out["verdict"] = "ok"
                out["reason"] = "stable_starved"
        except Exception:  # noqa: BLE001 — a signal-plane bug must not
            log.exception("lifecycle judgment failed")  # wedge the rollout
        return out

    # -------------------------------------------------------- transitions

    def _clear_canary(self) -> None:
        with self._lock:
            stable, canary = self._stable, self._canary
            self._canary = None
            self._fraction = 0.0
            self._route_seq = 0
        if self.watcher is not None and stable is not None:
            self._safe(lambda: self.watcher.unpin(stable))
        return canary

    def _promote(self, now: float, reason: str, judgment=None) -> None:
        with self._lock:
            canary = self._canary
            self._promoted_version = canary
            self._canary = None
            self._fraction = 0.0
            self._route_seq = 0
            old_stable = self._stable
            self._stable = canary
            self.promotes += 1
        if self.watcher is not None and old_stable is not None:
            # Release the rollback pin: retention may now retire the old
            # stable on its normal newest-K schedule.
            self._safe(lambda: self.watcher.unpin(old_stable))
        self._enter(PROMOTING, now, event="promoted", version=canary,
                    reason=reason)

    def _rollback(self, now: float, judgment: dict) -> None:
        with self._lock:
            canary = self._canary
            self._rolled_back_version = canary
            self._last_rollback = {
                "version": canary,
                "t": round(now, 3),
                "reason": judgment.get("reason"),
                "pair": judgment.get("pair"),
                "auc": judgment.get("auc"),
            }
            self.rollbacks += 1
        self._clear_canary()
        retired = False
        if self.watcher is not None:
            # Retire THROUGH the watcher: unload from the registry now
            # (traffic snaps back to stable — resolve's latest-version
            # default) AND blacklist, so the next reconcile pass cannot
            # hot-load the same bad version straight back from disk.
            retired = self._safe(lambda: self.watcher.retire(canary), False)
        if not retired:
            try:
                self.registry.unload(self.model, canary)
            except KeyError:
                pass  # already gone
        self._enter(ROLLED_BACK, now, event="rolled_back", version=canary,
                    reason=judgment.get("reason"))

    @staticmethod
    def _safe(fn, default=None):
        try:
            return fn()
        except Exception:  # noqa: BLE001 — watcher quirks must not
            log.exception("lifecycle watcher call failed")  # kill the tick
            return default

    # --------------------------------------------------------- fleet hooks

    def set_fleet_fraction(self, fraction: float | None) -> None:
        """Adopt the fleet-global ramp fraction (rollout coordinator via
        gossip); None returns routing to the local ramp schedule."""
        with self._lock:
            self._fleet_fraction = (
                None if fraction is None else float(fraction)
            )

    def force_rollback(self, reason: str = "forced") -> bool:
        """Roll back the live canary NOW without waiting for local
        quality evidence — the fleet-coordinated rollback path (another
        replica's judge fired) and the POST /lifecyclez/rollback
        operator surface. Returns False when no canary is live."""
        now = self._clock()
        with self._tick_mutex:
            with self._lock:
                if self._state != CANARY or self._canary is None:
                    return False
            self._rollback(now, {"verdict": "regressed", "reason": reason})
        return True

    def fleet_blacklist(self, version: int) -> str:
        """Apply a fleet-wide version blacklist entry locally: the live
        canary rolls back; a merely-loaded version is retired
        (unload + blacklist); an unseen version is blacklisted so the
        watcher can never hot-load it. The stable version is REFUSED —
        the fleet must never talk a replica out of its only good
        version. Returns the action taken (for /fleetz and tests)."""
        with self._lock:
            canary, stable = self._canary, self._stable
        if version == stable:
            return "refused_stable"
        if version == canary:
            return (
                "rolled_back"
                if self.force_rollback(reason="fleet_blacklist")
                else "noop"
            )
        if self.watcher is not None:
            if self._safe(lambda: self.watcher.is_blacklisted(version), False):
                return "already_blacklisted"
            if version in self._versions():
                self._safe(lambda: self.watcher.retire(version))
                return "retired"
            self._safe(lambda: self.watcher.blacklist(version))
            return "blacklisted"
        try:
            self.registry.unload(self.model, version)
            return "unloaded"
        except KeyError:
            return "noop"

    # ----------------------------------------------------------- publisher

    def publish_once(self, stop_evt: threading.Event | None = None) -> dict | None:
        """Run one fine-tune + publish round (the injected `publisher`
        callable, else the default train/publisher.py path against the
        current stable servable). Returns the publish summary or None on
        failure; failures count, never raise — the background loop must
        survive a flaky trainer. `stop_evt` is the calling loop's OWN
        stop event (an orphaned loop must answer to the generation that
        spawned it, not a successor's fresh event)."""
        if (stop_evt or self._stop).is_set():
            # A stop raced the loop's due-check (shutdown in progress):
            # a version must not be published into a draining stack.
            return None
        try:
            fn = self.publisher or self._default_publish
            summary = fn()
            with self._lock:
                self.publishes += 1
                self._last_publish_t = self._clock()
                # Under the lock: snapshot() iterates the deque there.
                self._events.append({
                    "t": round(self._clock(), 3), "state": self._state,
                    "event": "published",
                    "version": (summary or {}).get("version"),
                })
            return summary
        except Exception:  # noqa: BLE001
            with self._lock:
                self.publish_failures += 1
                self._last_publish_t = self._clock()  # back off a full interval
            log.exception("lifecycle publish failed")
            return None

    def _default_publish(self) -> dict:
        if self.watcher is None:
            raise RuntimeError(
                "fine-tune publishing needs a version watcher (the watched "
                "base dir is the publish target)"
            )
        from ..train.publisher import publish_finetuned

        cfg = self.config
        servable = self.registry.resolve(self.model)  # latest = stable
        return publish_finetuned(
            str(self.watcher.base_path),
            servable,
            kind=self.watcher.config.model_kind,
            steps=cfg.fine_tune_steps,
            batch_size=cfg.fine_tune_batch_size,
            learning_rate=cfg.fine_tune_learning_rate,
            seed=self.publishes + 1,  # fresh rows each round
        )

    def _publish_due(self, now: float) -> bool:
        cfg = self.config
        return (
            cfg.fine_tune_interval_s > 0
            and self._state == IDLE
            and now - self._last_publish_t >= cfg.fine_tune_interval_s
        )

    # ------------------------------------------------------------- thread

    def start(self) -> "LifecycleController":
        """Optional background driver: ticks at tick_interval_s and runs
        the fine-tune publisher when due. Tests with a fake clock never
        call this — tick() is the whole machine.

        Each start mints a FRESH stop event captured by the new loop: a
        restart after a timed-out stop() (the old thread detached mid-
        fine-tune) must not revive the orphan — its captured event stays
        set, so it exits at its next wait instead of becoming a second
        concurrent tick/publish loop."""
        if self._thread is None or not self._thread.is_alive():
            stop_evt = threading.Event()
            self._stop = stop_evt
            self._thread = threading.Thread(
                target=self._loop, args=(stop_evt,), name="lifecycle",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # Short join: a thread mid-fine-tune can run for minutes and
            # must not eat the caller's drain grace (GracefulShutdown
            # stops this BEFORE the watcher). publish_once re-checks the
            # stop flag, so a detached daemon thread at worst finishes
            # its training and discards the result.
            self._thread.join(timeout=2)
            if self._thread.is_alive():
                log.warning(
                    "lifecycle thread still inside a fine-tune/publish; "
                    "detaching (daemon thread). An already-started publish "
                    "may still land its version dir, but THIS process's "
                    "watcher is stopping and will never load it — the "
                    "artifact waits for the next server start"
                )
            self._thread = None
        # Drop the module-level criticality-scan gate the constructor
        # armed: a stopped controller routes nothing, so transports must
        # not keep paying the metadata scan for it.
        deactivate()

    def _loop(self, stop_evt: threading.Event) -> None:
        interval = max(self.config.tick_interval_s, 0.05)
        while not stop_evt.wait(interval):
            try:
                now = self._clock()
                self.tick(now)
                if self._publish_due(now) and not stop_evt.is_set():
                    # Fine-tune runs ON this thread: publishing is rare
                    # and IDLE-only, and a second thread would just race
                    # the state machine it feeds.
                    self.publish_once(stop_evt)
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("lifecycle tick failed; retrying next interval")

    # ------------------------------------------------------------ surfaces

    def fleet_record(self) -> dict:
        """The lifecycle slice of this replica's gossip record — cheap
        (no events copy, no watcher snapshot): published every gossip
        interval."""
        with self._lock:
            return {
                "canary": self._canary,
                "canary_fraction": round(self._fraction, 4),
                "rolled_back": self._rolled_back_version,
            }

    def snapshot(self) -> dict:
        """The /lifecyclez body, the `lifecycle` /monitoring block, and
        the dts_tpu_lifecycle_* Prometheus source."""
        now = self._clock()
        with self._lock:
            cfg = self.config
            out = {
                "enabled": True,
                "model": self.model,
                "paused": self._paused,
                "state": self._state,
                "state_age_s": round(now - self._state_since, 3),
                "stable_version": self._stable,
                "canary_version": self._canary,
                "canary_fraction": round(self._fraction, 4),
                "fleet_fraction": self._fleet_fraction,
                "promoted_version": self._promoted_version,
                "rolled_back_version": self._rolled_back_version,
                "counters": {
                    "ticks": self.ticks,
                    "promotes": self.promotes,
                    "rollbacks": self.rollbacks,
                    "publishes": self.publishes,
                    "publish_failures": self.publish_failures,
                    "routed_canary": self.routed_canary,
                    "routed_stable": self.routed_stable,
                    "routed_probe": self.routed_probe,
                },
                "last_judgment": self._last_judgment,
                "last_rollback": self._last_rollback,
                "events": list(self._events),
                "config": {
                    "tick_interval_s": cfg.tick_interval_s,
                    "canary_probe_only_s": cfg.canary_probe_only_s,
                    "canary_initial_fraction": cfg.canary_initial_fraction,
                    "canary_ramp_step": cfg.canary_ramp_step,
                    "canary_step_dwell_s": cfg.canary_step_dwell_s,
                    "canary_max_fraction": cfg.canary_max_fraction,
                    "promote_after_s": cfg.promote_after_s,
                    "min_canary_scores": cfg.min_canary_scores,
                    "rollback_psi": cfg.rollback_psi,
                    "rollback_auc_drop": cfg.rollback_auc_drop,
                    "rollback_hold_s": cfg.rollback_hold_s,
                    "fine_tune_interval_s": cfg.fine_tune_interval_s,
                },
            }
        out["versions_loaded"] = self._versions()
        if self.watcher is not None:
            out["watcher"] = self._safe(self.watcher.snapshot)
        return out
