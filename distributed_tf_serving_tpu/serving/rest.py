"""TF-Serving-compatible REST gateway (the :8501 surface).

`tensorflow_model_server` serves every model on two ports: gRPC (:8500)
and a JSON REST API (:8501) with the `/v1/models/...` routes. The
reference client speaks gRPC only (DCNClient.java), but the ecosystem the
reference lives in — dashboards, canary probes, curl debugging — uses the
REST surface constantly; a drop-in replacement must answer it.

Routes (TF-Serving REST API v1 semantics; every POST verb also accepts
`/versions/{v}` or `/labels/{l}` segments — label routing matches the
model server's version_labels map):
- `POST /v1/models/{model}[/versions/{v}|/labels/{l}]:predict`
  body `{"instances": [...]}` (row format: one dict per instance, or the
  bare value for single-input models) -> `{"predictions": [...]}`;
  body `{"inputs": {...}}` (columnar) -> `{"outputs": ...}` (dict when
  the signature has several outputs, bare tensor when one);
  optional `"signature_name"`.
- `POST /v1/models/{model}[/versions/{v}]:classify` and `...:regress`
  body `{"examples": [{feat: val, ...}, ...], "context": {...}?}` ->
  `{"results": [...]}` (label/score pairs per example for classify, one
  value per example for regress), riding the same Example plane as the
  gRPC Classify/Regress RPCs (`example_codec.decode_input`).
- `GET  /v1/models/{model}` -> version status list.
- `GET  /v1/models/{model}/metadata` -> signature metadata (JSON).
- `GET  /monitoring/prometheus/metrics` -> Prometheus text exposition
  (the model server's monitoring endpoint; TF-Serving metric names).
- `GET  /monitoring[?section=NAME]` -> the metrics snapshot as JSON
  (rolling-window QPS + windowed percentiles next to lifetime values,
  per-model blocks, batcher gauges, phase means, one block per armed
  plane; ?section serves a single block without building the rest).
- `GET  /qualityz`, `POST /qualityz/snapshot`, `POST /labelz` -> the
  model-quality plane (serving/quality.py): score sketches + drift,
  reference pinning, label-feedback ingest.
- `GET  /tracez[?format=chrome][&limit=N]` -> the trace plane
  (utils/tracing.py): recent + slowest retained span trees as JSON, or a
  Perfetto-loadable Chrome-trace-event export.

Requests are converted to the SAME PredictRequest protos the gRPC path
parses and handed to PredictionServiceImpl.predict_async — one
implementation of resolution, validation, widening, batching, and error
taxonomy; the gateway only translates JSON<->tensors and ServiceError
codes onto HTTP statuses (TF-Serving's own REST error shape:
`{"error": "..."}`).
"""

from __future__ import annotations

import json
import logging
import time

import numpy as np
from aiohttp import web

from .. import codec
from ..proto import serving_apis_pb2 as apis
from ..utils import tracing
from ..utils.tracing import request_trace
from . import lifecycle as lifecycle_mod
from . import overload as overload_mod
from .service import PredictionServiceImpl, ServiceError

log = logging.getLogger("dts_tpu.rest")

_HTTP_STATUS = {
    "NOT_FOUND": 404,
    "INVALID_ARGUMENT": 400,
    "RESOURCE_EXHAUSTED": 429,
    "UNAVAILABLE": 503,
    "DEADLINE_EXCEEDED": 504,
    "INTERNAL": 500,
}


def _json_error(
    code: str, message: str, retry_after_ms: int | None = None
) -> web.Response:
    resp = web.json_response(
        {"error": message}, status=_HTTP_STATUS.get(code, 500)
    )
    if retry_after_ms:
        # Overload pushback (serving/overload.py): the standard header in
        # whole seconds (ceil — a 25 ms hint must not round to "now") plus
        # the precise hint the in-tree client honors.
        resp.headers["Retry-After"] = str(max((retry_after_ms + 999) // 1000, 1))
        resp.headers[overload_mod.RETRY_AFTER_KEY] = str(int(retry_after_ms))
    return resp


def _criticality_of(request: web.Request) -> str | None:
    """The request's criticality lane from the x-dts-criticality header.
    Only scanned while a plane that consumes it is armed (overload lane
    shedding, or lifecycle probe-lane canary routing)."""
    if not (overload_mod.active() or lifecycle_mod.active()):
        return None
    value = request.headers.get(overload_mod.CRITICALITY_KEY)
    return overload_mod.normalize_criticality(value) if value else None


def _mark_degraded(resp: web.Response) -> web.Response:
    """Brownout stale-serves announce themselves in an X-DTS-Degraded
    response header, mirroring the gRPC trailing-metadata marker (the
    contextvar is task-local, so this request's handler task sees exactly
    its own marker)."""
    if overload_mod.active():
        degraded = overload_mod.consume_degraded()
        if degraded:
            resp.headers[overload_mod.DEGRADED_KEY] = degraded
    return resp


class RestGateway:
    """aiohttp application exposing a PredictionServiceImpl over REST.

    When a ServerMetrics is provided (the server CLI passes the gRPC
    server's instance, so both surfaces aggregate in one place), every
    REST request is observed under a `REST.<Verb>` entrypoint and the
    gateway answers `GET /monitoring/prometheus/metrics` — the model
    server's monitoring endpoint (enabled there via --monitoring_config_
    file; always on here, it is read-only and costs nothing when
    unscraped)."""

    def __init__(self, impl: PredictionServiceImpl, metrics=None):
        from ..utils.metrics import ServerMetrics

        self.impl = impl
        self.metrics = metrics or ServerMetrics()
        self.app = web.Application(client_max_size=256 * 1024 * 1024)
        self.app.add_routes([
            web.post("/v1/models/{model}:predict", self.predict),
            web.post(
                "/v1/models/{model}/versions/{version}:predict", self.predict
            ),
            web.post(
                "/v1/models/{model}/labels/{label}:predict", self.predict
            ),
            web.post("/v1/models/{model}:classify", self.classify),
            web.post(
                "/v1/models/{model}/versions/{version}:classify", self.classify
            ),
            web.post(
                "/v1/models/{model}/labels/{label}:classify", self.classify
            ),
            web.post("/v1/models/{model}:regress", self.regress),
            web.post(
                "/v1/models/{model}/versions/{version}:regress", self.regress
            ),
            web.post(
                "/v1/models/{model}/labels/{label}:regress", self.regress
            ),
            web.get("/v1/models/{model}", self.status),
            web.get("/v1/models/{model}/versions/{version}", self.status),
            web.get("/v1/models/{model}/labels/{label}", self.status),
            web.get("/v1/models/{model}/metadata", self.metadata),
            web.get(
                "/v1/models/{model}/versions/{version}/metadata", self.metadata
            ),
            web.get("/v1/models/{model}/labels/{label}/metadata", self.metadata),
            web.get("/monitoring/prometheus/metrics", self.prometheus),
            # Live-telemetry plane (ISSUE 3): the JSON twin of the
            # Prometheus surface (rolling-window QPS/percentiles next to
            # lifetime values, per-model blocks, batcher gauges, phase
            # means) and the trace viewer (recent + slowest span trees;
            # ?format=chrome exports Perfetto-loadable trace-event JSON).
            web.get("/monitoring", self.monitoring),
            web.get("/tracez", self.tracez),
            # Fleet trace export (ISSUE 18): incremental kept-span pull
            # for a router-side TraceCollector (also mounted on the
            # gossip port when the fleet plane is armed).
            web.get("/tracez/export", self.tracez_export),
            # Cache plane (ISSUE 4): per-model hit/miss/coalesced/eviction
            # counters + occupancy/config, and the operator flush control.
            web.get("/cachez", self.cachez),
            web.post("/cachez/flush", self.cachez_flush),
            # Utilization plane (ISSUE 6): the occupancy ledger's gap
            # waterfall (wall time decomposed into device/H2D/D2H plus
            # idle-by-cause, summing to wall) + the live
            # achieved_fraction_of_device_limit estimate, and on-demand
            # deep capture (jax.profiler device trace + host-thread stack
            # sampling over one window).
            web.get("/utilz", self.utilz),
            web.get("/profilez", self.profilez_status),
            web.post("/profilez/start", self.profilez_start),
            # Model-quality plane (ISSUE 7): per-(model, version) score
            # sketches + PSI/JS drift (vs the pinned reference and between
            # live versions) + label-join AUC/calibration, the reference-
            # pinning control, and the label-feedback ingest.
            web.get("/qualityz", self.qualityz),
            web.post("/qualityz/snapshot", self.qualityz_snapshot),
            web.post("/labelz", self.labelz),
            # Lifecycle plane (ISSUE 8): the continuous-freshness state
            # machine — canary routing fractions/counters, promote/
            # rollback history, and the version watcher's blacklist/pin
            # state.
            web.get("/lifecyclez", self.lifecyclez),
            # Operator rollback lever (ISSUE 17): demote the live canary
            # NOW — the same path the quality gate takes, so the fleet
            # coordinator sees rolled_back in the next gossip record and
            # blacklists the version fleet-wide.
            web.post("/lifecyclez/rollback", self.lifecyclez_rollback),
            # Fleet plane (ISSUE 17): this member's gossip view — every
            # known replica/router record, exchange counters, and the
            # rollout follower/coordinator state.
            web.get("/fleetz", self.fleetz),
            # Recovery plane (ISSUE 11): the device-failure recovery
            # state machine — quarantine/reinit/replay counters, the
            # poisoned-input bisection verdicts, and the last cycle's
            # duration (the live MTTR evidence).
            web.get("/recoveryz", self.recoveryz),
            # Mesh serving mode (ISSUE 13/15): geometry, device list,
            # executor pad/layout counters — and, with [elastic] armed,
            # the current split, switch history ring, and per-split
            # serve counters.
            web.get("/meshz", self.meshz),
            # Multi-stage ranking cascade (ISSUE 19): stage-1/prune/
            # stage-2 counters, row dispositions, observed survivor and
            # rank fractions, and the survivor-bucket histogram.
            web.get("/cascadez", self.cascadez),
            # Data-integrity plane (ISSUE 20): wire-checksum / readback-
            # screen / shadow-verification counters + suspect state and
            # the detection-event history, and the operator lever that
            # forces the NEXT batches through shadow verification.
            web.get("/integrityz", self.integrityz),
            web.post("/integrityz/audit", self.integrityz_audit),
        ])

    # ------------------------------------------------------------- helpers

    def _resolve_specs(
        self, model: str, version, signature_name: str, label=None,
        criticality=None,
    ):
        # ONE lookup-error taxonomy, shared with the gRPC path. The
        # lifecycle plane's canary router overrides DEFAULT resolutions
        # here too — the gateway pins the CONCRETE resolved version into
        # the proto it hands the impl, so routing must happen at this
        # resolve or REST traffic would never carry canary share.
        from .service import _wrap_lookup

        routed = self.impl.lifecycle_route(model, version, label, criticality)
        if routed is not None:
            try:
                servable = self.impl.registry.resolve(model, routed)
            except KeyError:
                # Routed version vanished mid-swap (rollback racing this
                # request): serve the latest instead of failing traffic.
                servable = _wrap_lookup(
                    lambda: self.impl.registry.resolve(model)
                )
        else:
            servable = _wrap_lookup(
                lambda: self.impl.registry.resolve(model, version, label)
            )
        sig = _wrap_lookup(lambda: servable.signature(signature_name))
        return servable, sig

    @staticmethod
    def _parse_version(raw) -> int | None:
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError as e:
            # A non-numeric /versions/{v} segment is a CLIENT error, not an
            # internal one (label routing rides /labels/{l} instead).
            raise ServiceError(
                "INVALID_ARGUMENT", f"version must be an integer, got {raw!r}"
            ) from e

    @staticmethod
    def _fill_model_spec(spec, model: str, version: int | None, label) -> None:
        """ONE place that turns route segments into a ModelSpec, for all
        three POST verbs (version and label arrive from distinct routes, so
        the upstream oneof exclusivity holds by construction here; the
        service still enforces it for raw proto callers)."""
        spec.name = model
        if version is not None:
            spec.version.value = version
        if label:
            spec.version_label = label

    @staticmethod
    def _arrays_from_instances(instances, sig) -> dict[str, np.ndarray]:
        if not isinstance(instances, list) or not instances:
            raise ServiceError(
                "INVALID_ARGUMENT", "instances must be a non-empty list"
            )
        specs = sig.input_specs
        if isinstance(instances[0], dict):
            columns: dict[str, list] = {}
            for i, inst in enumerate(instances):
                if not isinstance(inst, dict):
                    raise ServiceError(
                        "INVALID_ARGUMENT",
                        f"instance {i} is not an object (mixed row formats)",
                    )
                for k, v in inst.items():
                    columns.setdefault(k, []).append(v)
            if any(len(v) != len(instances) for v in columns.values()):
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    "every instance must carry the same input names",
                )
        else:
            # Bare-value shorthand: legal only for single-input signatures
            # (TF-Serving REST API rule).
            if len(specs) != 1:
                raise ServiceError(
                    "INVALID_ARGUMENT",
                    "bare-value instances require a single-input signature; "
                    f"this one expects {sorted(specs)}",
                )
            columns = {next(iter(specs)): instances}
        return RestGateway._to_ndarrays(columns, specs)

    @staticmethod
    def _to_ndarrays(columns: dict, specs) -> dict[str, np.ndarray]:
        arrays = {}
        for name, vals in columns.items():
            spec = specs.get(name)
            np_dtype = codec.dtype_to_numpy(spec.dtype) if spec else None
            try:
                arrays[name] = np.asarray(vals, dtype=np_dtype)
            except (TypeError, ValueError, OverflowError) as e:
                raise ServiceError(
                    "INVALID_ARGUMENT", f"input {name!r}: {e}"
                ) from e
        return arrays

    # -------------------------------------------------------------- routes

    async def _observed(self, name: str, handler, request) -> web.Response:
        t0 = time.perf_counter()
        if overload_mod.active():
            # Clear any degraded marker a FAILED previous request left in
            # this context (markers are consumed only on the success path,
            # and aiohttp reuses one task per keep-alive connection).
            overload_mod.consume_degraded()
        model = request.match_info.get("model")
        if tracing.enabled():
            # Server root span for the REST surface: adopts the caller's
            # trace via the standard W3C `traceparent` HTTP header.
            with tracing.start_root(
                f"server.{name}",
                traceparent=request.headers.get("traceparent"),
                attrs={"entrypoint": name, **({"model": model} if model else {})},
            ) as span:
                resp = await handler(request)
                # span can be None: disable() racing this request makes
                # start_root yield the no-op context mid-flight.
                if span is not None and resp.status >= 400:
                    span.status = "ERROR"
                    span.attrs["http_status"] = resp.status
        else:
            resp = await handler(request)
        self.metrics.observe(
            name, time.perf_counter() - t0, resp.status < 400, model=model
        )
        return resp

    async def predict(self, request: web.Request) -> web.Response:
        return await self._observed("REST.Predict", self._predict, request)

    async def _predict(self, request: web.Request) -> web.Response:
        model = request.match_info["model"]
        try:
            version = self._parse_version(request.match_info.get("version"))
            label = request.match_info.get("label")
            try:
                body = await request.json()
            except Exception as e:  # noqa: BLE001 — malformed JSON is a 400
                return _json_error("INVALID_ARGUMENT", f"invalid JSON body: {e}")
            if not isinstance(body, dict):
                return _json_error("INVALID_ARGUMENT", "body must be a JSON object")
            signature_name = body.get("signature_name", "")
            row_format = "instances" in body
            if row_format == ("inputs" in body):
                return _json_error(
                    "INVALID_ARGUMENT",
                    'body must carry exactly one of "instances" or "inputs"',
                )
            servable, sig = self._resolve_specs(
                model, version, signature_name, label,
                criticality=_criticality_of(request),
            )
            if row_format:
                arrays = self._arrays_from_instances(body["instances"], sig)
            else:
                cols = body["inputs"]
                if not isinstance(cols, dict):
                    # Bare columnar tensor: single-input shorthand.
                    specs = sig.input_specs
                    if len(specs) != 1:
                        return _json_error(
                            "INVALID_ARGUMENT",
                            "bare inputs require a single-input signature",
                        )
                    cols = {next(iter(specs)): cols}
                arrays = self._to_ndarrays(cols, sig.input_specs)

            # ONE semantics path: the same proto the gRPC surface parses.
            # The spec pins the CONCRETE version this gateway just resolved
            # (and validated inputs against) — re-sending the label (or an
            # absent version) would let the impl re-resolve, and a label
            # retarget / hot-swap landing between decode and execute would
            # pair one version's signature with another's execution.
            req = apis.PredictRequest()
            self._fill_model_spec(req.model_spec, model, servable.version, None)
            req.model_spec.signature_name = signature_name
            for key, arr in arrays.items():
                codec.from_ndarray(
                    arr, use_tensor_content=True, out=req.inputs[key]
                )
            resp = await self.impl.predict_async(
                req, criticality=_criticality_of(request)
            )
            outputs = {
                k: codec.to_ndarray(v).tolist() for k, v in resp.outputs.items()
            }
            if row_format:
                names = list(outputs)
                if len(names) == 1:
                    predictions = outputs[names[0]]
                else:
                    n = len(next(iter(outputs.values())))
                    predictions = [
                        {k: outputs[k][i] for k in names} for i in range(n)
                    ]
                return _mark_degraded(
                    web.json_response({"predictions": predictions})
                )
            if len(outputs) == 1:
                return _mark_degraded(
                    web.json_response({"outputs": next(iter(outputs.values()))})
                )
            return _mark_degraded(web.json_response({"outputs": outputs}))
        except ServiceError as e:
            return _json_error(
                e.code, str(e), retry_after_ms=e.retry_after_ms
            )
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            log.exception("internal error serving REST predict")
            return _json_error("INTERNAL", f"internal error: {e}")

    # ------------------------------------------------- classify / regress

    @staticmethod
    def _feature_from_json(key: str, value, feature) -> None:
        """Fill one tf.Example Feature from a JSON value (TF-Serving REST
        Example encoding: scalars or flat lists; ints -> int64_list, floats
        -> float_list with int coercion, strings -> bytes_list, and
        `{"b64": ...}` objects for binary — json_tensor.cc semantics)."""
        import base64

        vals = value if isinstance(value, list) else [value]
        if not vals:
            raise ServiceError(
                "INVALID_ARGUMENT", f"feature {key!r}: empty value list"
            )
        if any(isinstance(v, float) for v in vals):
            try:
                feature.float_list.value.extend(float(v) for v in vals)
            except (TypeError, ValueError) as e:
                raise ServiceError(
                    "INVALID_ARGUMENT", f"feature {key!r}: {e}"
                ) from e
        elif all(isinstance(v, bool) is False and isinstance(v, int) for v in vals):
            try:
                feature.int64_list.value.extend(vals)
            except ValueError as e:  # out of int64 range is a client error
                raise ServiceError(
                    "INVALID_ARGUMENT", f"feature {key!r}: {e}"
                ) from e
        elif all(isinstance(v, str) for v in vals):
            feature.bytes_list.value.extend(v.encode("utf-8") for v in vals)
        elif all(isinstance(v, dict) and set(v) == {"b64"} for v in vals):
            try:
                feature.bytes_list.value.extend(
                    base64.b64decode(v["b64"]) for v in vals
                )
            except Exception as e:  # noqa: BLE001 — bad base64 is a 400
                raise ServiceError(
                    "INVALID_ARGUMENT", f"feature {key!r}: invalid base64: {e}"
                ) from e
        else:
            raise ServiceError(
                "INVALID_ARGUMENT",
                f"feature {key!r}: values must be all-int, all-float "
                "(ints coerce), all-string, or all-b64 objects",
            )

    def _example_from_json(self, obj, index: int):
        from ..proto import tf_example_pb2 as ex

        if not isinstance(obj, dict):
            raise ServiceError(
                "INVALID_ARGUMENT", f"example {index} is not a JSON object"
            )
        example = ex.Example()
        for key, value in obj.items():
            self._feature_from_json(
                key, value, example.features.feature[key]
            )
        return example

    def _build_example_request(self, request: web.Request, req, body: dict) -> None:
        """Shared :classify/:regress body parsing into a Classification/
        RegressionRequest's model_spec + Input (examples [+ context])."""
        model = request.match_info["model"]
        version = self._parse_version(request.match_info.get("version"))
        self._fill_model_spec(
            req.model_spec, model, version, request.match_info.get("label")
        )
        req.model_spec.signature_name = body.get("signature_name", "")
        examples = body.get("examples")
        if not isinstance(examples, list) or not examples:
            raise ServiceError(
                "INVALID_ARGUMENT", 'body must carry a non-empty "examples" list'
            )
        context = body.get("context")
        if context is not None:
            target = req.input.example_list_with_context
            target.context.CopyFrom(self._example_from_json(context, -1))
            dest = target.examples
        else:
            dest = req.input.example_list.examples
        for i, obj in enumerate(examples):
            dest.append(self._example_from_json(obj, i))

    async def _example_route(self, request: web.Request, kind: str) -> web.Response:
        try:
            try:
                body = await request.json()
            except Exception as e:  # noqa: BLE001 — malformed JSON is a 400
                return _json_error("INVALID_ARGUMENT", f"invalid JSON body: {e}")
            if not isinstance(body, dict):
                return _json_error("INVALID_ARGUMENT", "body must be a JSON object")
            if kind == "classify":
                req = apis.ClassificationRequest()
                self._build_example_request(request, req, body)
                resp = await self.impl.classify_async(
                    req, criticality=_criticality_of(request)
                )
                # TF-Serving REST shape (json_tensor.cc): one
                # [[label, score], ...] list per example, same order.
                results = [
                    [[c.label, c.score] for c in cls.classes]
                    for cls in resp.result.classifications
                ]
            else:
                req = apis.RegressionRequest()
                self._build_example_request(request, req, body)
                resp = await self.impl.regress_async(
                    req, criticality=_criticality_of(request)
                )
                results = [r.value for r in resp.result.regressions]
            return _mark_degraded(web.json_response({"results": results}))
        except ServiceError as e:
            return _json_error(
                e.code, str(e), retry_after_ms=e.retry_after_ms
            )
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            log.exception("internal error serving REST %s", kind)
            return _json_error("INTERNAL", f"internal error: {e}")

    async def classify(self, request: web.Request) -> web.Response:
        return await self._observed(
            "REST.Classify",
            lambda r: self._example_route(r, "classify"),
            request,
        )

    async def regress(self, request: web.Request) -> web.Response:
        return await self._observed(
            "REST.Regress",
            lambda r: self._example_route(r, "regress"),
            request,
        )

    async def prometheus(self, request: web.Request) -> web.Response:
        stats = getattr(self.impl.batcher, "stats", None)
        # Computed once and shared downstream: mesh_stats lifts its
        # per-device attribution from the utilization snapshot, and
        # elastic_stats lifts its block from the mesh snapshot — one
        # snapshot each per scrape, never recomputed.
        utilization = self.impl.utilization_stats()
        mesh = self.impl.mesh_stats(utilization=utilization)
        return web.Response(
            body=self.metrics.prometheus_text(
                stats, cache=self.impl.cache_stats(),
                row_cache=self.impl.row_cache_stats(),
                overload=self.impl.overload_stats(),
                utilization=utilization,
                quality=self.impl.quality_stats(),
                lifecycle=self.impl.lifecycle_stats(),
                pipeline=self.impl.pipeline_stats(),
                recovery=self.impl.recovery_stats(),
                kernels=self.impl.kernels_stats(),
                mesh=mesh,
                elastic=self.impl.elastic_stats(mesh=mesh),
                fleet=self.impl.fleet_stats(),
                cascade=self.impl.cascade_stats(),
                integrity=self.impl.integrity_stats(),
            ).encode("utf-8"),
            headers={
                "Content-Type": "text/plain; version=0.0.4; charset=utf-8"
            },
        )

    def _monitoring_builders(self) -> dict:
        """One builder per /monitoring block, so ?section=NAME serves a
        single block WITHOUT serializing — or even computing — the other
        planes' snapshots (the JSON now aggregates 8+ blocks; scrapers
        that want one should not pay for all)."""

        def request_log():
            logger = getattr(self.impl, "request_logger", None)
            return logger.stats() if logger is not None else None

        return {
            "metrics": lambda: self.metrics.snapshot(
                getattr(self.impl.batcher, "stats", None)
            ),
            "phases": request_trace.snapshot,
            "tracing": lambda: {
                "enabled": tracing.enabled(),
                "recorded": tracing.recorder().recorded,
            },
            "cache": self.impl.cache_stats,
            "row_cache": self.impl.row_cache_stats,
            "overload": self.impl.overload_stats,
            "utilization": self.impl.utilization_stats,
            "quality": self.impl.quality_stats,
            "lifecycle": self.impl.lifecycle_stats,
            "recovery": self.impl.recovery_stats,
            "kernels": self.impl.kernels_stats,
            "mesh": self.impl.mesh_stats,
            "elastic": self.impl.elastic_stats,
            "fleet": self.impl.fleet_stats,
            "cascade": self.impl.cascade_stats,
            "integrity": self.impl.integrity_stats,
            "versions": self.impl.versions_stats,
            "pipeline": self.impl.pipeline_stats,
            "request_log": request_log,
            "draining": lambda: bool(getattr(self.impl, "draining", False)),
        }

    async def monitoring(self, request: web.Request) -> web.Response:
        """GET /monitoring[?section=NAME]: the metrics snapshot as JSON —
        rolling-window qps + windowed percentiles next to the lifetime
        values, per-model blocks, batcher gauges, the aggregate phase
        means, and one block per armed plane (cache / overload /
        utilization / quality / request_log). ?section=NAME returns just
        that block (and skips building the rest server-side); a disabled
        plane's section answers null, an unknown name is a 400."""
        builders = self._monitoring_builders()
        section = request.query.get("section")
        if section is not None:
            builder = builders.get(section)
            if builder is None:
                return _json_error(
                    "INVALID_ARGUMENT",
                    f"unknown section {section!r}; have {sorted(builders)}",
                )
            return web.json_response({section: builder()})
        snap = builders["metrics"]()
        snap["phases"] = builders["phases"]()
        snap["tracing"] = builders["tracing"]()
        # Armed-plane blocks only: a disabled plane is absent, so
        # dashboards can distinguish "off" from "cold". The mesh block
        # reuses the utilization snapshot computed earlier in this same
        # pass (its per-device attribution lifts from it — no second
        # waterfall merge).
        for name in ("cache", "row_cache", "overload", "utilization",
                     "quality", "lifecycle", "recovery", "kernels", "mesh",
                     "elastic", "fleet", "cascade", "integrity", "versions",
                     "pipeline"):
            if name == "mesh":
                block = self.impl.mesh_stats(
                    utilization=snap.get("utilization")
                )
            elif name == "elastic":
                # Lifted from the mesh block computed just above in this
                # same pass — never a second executor/history walk.
                block = self.impl.elastic_stats(mesh=snap.get("mesh"))
            else:
                block = builders[name]()
            if block is not None:
                snap[name] = block
        snap["draining"] = builders["draining"]()
        log_block = builders["request_log"]()
        if log_block is not None:
            # Written/dropped accounting for the sampled PredictionLog
            # writer — a silently-shedding log queue must be visible here.
            snap["request_log"] = log_block
        return web.json_response(snap)

    async def tracez(self, request: web.Request) -> web.Response:
        """GET /tracez: recent + slowest retained span trees as JSON;
        ?format=chrome returns Chrome-trace-event JSON (Perfetto /
        chrome://tracing loadable); ?limit=N bounds the trace list."""
        rec = tracing.recorder()
        dumps = lambda obj: json.dumps(obj, default=str)  # noqa: E731
        if request.query.get("format") == "chrome":
            return web.json_response(rec.chrome_trace(), dumps=dumps)
        try:
            limit = int(request.query.get("limit", "50"))
        except ValueError:
            return _json_error("INVALID_ARGUMENT", "limit must be an integer")
        body = rec.tracez(limit=limit)
        body["enabled"] = tracing.enabled()
        return web.json_response(body, dumps=dumps)

    async def tracez_export(self, request: web.Request) -> web.Response:
        """GET /tracez/export?since=CURSOR: kept span trees after the
        cursor, with this process's clock anchor (the fleet stitcher's
        pull surface). `{"enabled": false}` while tracing is off."""
        if not tracing.enabled():
            return web.json_response(
                {"enabled": False, "cursor": 0, "spans": []}
            )
        try:
            since = int(request.query.get("since", "0") or 0)
        except ValueError:
            return _json_error("INVALID_ARGUMENT", "since must be an integer")
        return web.json_response(
            tracing.recorder().export_since(since),
            dumps=lambda obj: json.dumps(obj, default=str),
        )

    async def utilz(self, request: web.Request) -> web.Response:
        """GET /utilz[?window=S]: the utilization-attribution surface —
        occupancy ledger counters, idle-gap histogram by blocking cause,
        the windowed gap waterfall (components sum to wall), and the live
        achieved_fraction_of_device_limit estimate. `{"enabled": false}`
        when no ledger is armed ([utilization] enabled=false), so probes
        need no config knowledge."""
        window = request.query.get("window")
        if window is not None:
            try:
                window = float(window)
            except ValueError:
                return _json_error("INVALID_ARGUMENT", "window must be a number")
        stats = self.impl.utilization_stats(window)
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def profilez_status(self, request: web.Request) -> web.Response:
        """GET /profilez: is a deep capture running, and where will its
        artifacts land."""
        from .utilization import profiler_capture

        return web.json_response(profiler_capture().status())

    async def profilez_start(self, request: web.Request) -> web.Response:
        """POST /profilez/start?seconds=N: one-shot deep capture —
        jax.profiler device trace + host-thread stack sampling over the
        same window (tools/profile_host.py methodology). Returns the
        artifact paths immediately; the capture stops itself after N
        seconds. A concurrent capture is refused with 409 (the jax
        profiler is process-global)."""
        from .utilization import CaptureInProgressError, profiler_capture

        try:
            seconds = float(request.query.get("seconds", "3"))
        except ValueError:
            return _json_error("INVALID_ARGUMENT", "seconds must be a number")
        try:
            info = profiler_capture().start(seconds)
        except CaptureInProgressError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"started": True, **info})

    async def qualityz(self, request: web.Request) -> web.Response:
        """GET /qualityz[?model=NAME][&version=V]: the model-quality
        surface — per-(model, version) score sketches (lifetime + rolling
        window, per-lane counts), PSI/JS drift vs the pinned reference
        and between live versions, label-join AUC/calibration, and the
        exemplar counters. `{"enabled": false}` when no monitor is armed
        ([quality] enabled=false), so probes need no config knowledge."""
        version = request.query.get("version")
        if version is not None:
            try:
                version = int(version)
            except ValueError:
                return _json_error(
                    "INVALID_ARGUMENT", "version must be an integer"
                )
        stats = self.impl.quality_stats(
            model=request.query.get("model") or None, version=version
        )
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def qualityz_snapshot(self, request: web.Request) -> web.Response:
        """POST /qualityz/snapshot: pin the current windowed score
        distributions as the drift reference (and persist the artifact —
        [quality] reference_file, default artifacts/quality_reference
        .json). Future windows drift AGAINST this pin until the next."""
        try:
            pinned = self.impl.quality_pin_reference()
        except ServiceError as e:
            return _json_error(e.code, str(e))
        return web.json_response({"pinned": True, **pinned})

    async def labelz(self, request: web.Request) -> web.Response:
        """POST /labelz: the label-feedback ingest. Body: one label
        object `{"id": ..., "label": 0|1, "ts": ...?}` or
        `{"labels": [...]}`; `id` is a request trace id (optionally
        `#<row>`) or a per-row feature digest (client.label_keys /
        quality.row_label_keys). Answers joined/orphaned counts for this
        call — an orphaned label (unknown or evicted key) is reported,
        never silently dropped."""
        try:
            body = await request.json()
        except Exception as e:  # noqa: BLE001 — malformed JSON is a 400
            return _json_error("INVALID_ARGUMENT", f"invalid JSON body: {e}")
        if isinstance(body, dict) and "labels" in body:
            items = body["labels"]
        elif isinstance(body, dict):
            items = [body]
        else:
            items = body
        if not isinstance(items, list) or not items:
            return _json_error(
                "INVALID_ARGUMENT",
                'body must be a label object, a list, or {"labels": [...]}',
            )
        try:
            result = self.impl.quality_ingest_labels(items)
        except ServiceError as e:
            return _json_error(e.code, str(e))
        return web.json_response(result)

    async def lifecyclez(self, request: web.Request) -> web.Response:
        """GET /lifecyclez: the continuous-freshness surface — the
        IDLE/CANARY/PROMOTING/ROLLED_BACK state machine, stable/canary
        versions and the live routing fraction, publish/promote/rollback
        counters + transition history, the last rollback's evidence
        (pair PSI/JS, AUC deltas), and the version watcher's
        loaded/on-disk/blacklisted/pinned sets. `{"enabled": false}` when
        no controller is armed ([lifecycle] enabled=false), so probes
        need no config knowledge."""
        stats = self.impl.lifecycle_stats()
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def lifecyclez_rollback(self, request: web.Request) -> web.Response:
        """POST /lifecyclez/rollback: operator-forced demotion of the
        live canary — the SAME path the drift/AUC gate takes (retire +
        blacklist + restore stable), so the fleet coordinator's next
        tick sees `rolled_back` in this replica's gossip record and
        blacklists the version on EVERY replica. Body (optional JSON):
        {"reason": "..."}. 409 when there is no canary to roll back;
        `{"enabled": false}` + 404 when no controller is armed."""
        lifecycle = getattr(self.impl, "lifecycle", None)
        if lifecycle is None:
            return web.json_response({"enabled": False}, status=404)
        reason = "operator"
        try:
            body = await request.json()
            if isinstance(body, dict) and body.get("reason"):
                reason = str(body["reason"])
        except Exception:  # noqa: BLE001 — empty body is fine
            pass
        rolled = lifecycle.force_rollback(reason)
        return web.json_response(
            {"rolled_back": rolled, "reason": reason,
             "lifecycle": self.impl.lifecycle_stats()},
            status=200 if rolled else 409,
        )

    async def fleetz(self, request: web.Request) -> web.Response:
        """GET /fleetz: this member's fleet view — gossip membership
        (every known replica/router record with state/pressure/versions/
        canary fields), exchange + record-disposition counters, and the
        rollout follower state. `{"enabled": false}` when the replica is
        not fleet-joined ([fleet] enabled=false), so probes need no
        config knowledge."""
        plane = getattr(self.impl, "fleet", None)
        if plane is None:
            return web.json_response({"enabled": False})
        return web.json_response({"enabled": True, **plane.snapshot()})

    async def cascadez(self, request: web.Request) -> web.Response:
        """GET /cascadez: the multi-stage ranking cascade surface —
        config echo (stage-1 model, survivor policy), request/fallback/
        stage-1-failure counters, row dispositions (requested/survivor/
        pruned), per-stage wall time, observed survivor- and rank-
        fractions, and the survivor-bucket histogram (which padded rungs
        the stage-2 submits landed in). `{"enabled": false}` when the
        cascade is not armed ([cascade] enabled=false), so probes need
        no config knowledge."""
        stats = self.impl.cascade_stats()
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def integrityz(self, request: web.Request) -> web.Response:
        """GET /integrityz: the data-integrity surface — wire-checksum
        verify/reject counters, readback-screen trips, shadow-
        verification batch/mismatch counters, the replica's suspect
        verdict (what the fleet record gossips), escalations into the
        recovery plane, and the detection-event history. `{"enabled":
        false}` when the plane is not armed ([integrity] enabled=false),
        so probes need no config knowledge."""
        stats = self.impl.integrity_stats()
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def integrityz_audit(self, request: web.Request) -> web.Response:
        """POST /integrityz/audit[?batches=N]: operator-forced shadow
        verification — the NEXT N batches (default 1) re-execute through
        the same jitted entry and compare bit-identically, regardless of
        shadow_fraction. The on-demand lever for "is this replica
        corrupting right now?". 404 + `{"enabled": false}` when the
        plane is not armed."""
        integ = getattr(self.impl, "integrity", None)
        if integ is None:
            return web.json_response({"enabled": False}, status=404)
        try:
            batches = int(request.query.get("batches", "1"))
        except ValueError:
            return _json_error("INVALID_ARGUMENT", "batches must be an integer")
        if batches < 1:
            return _json_error("INVALID_ARGUMENT", "batches must be >= 1")
        pending = integ.request_audit(batches)
        return web.json_response(
            {"requested": batches, "pending_audits": pending}
        )

    async def recoveryz(self, request: web.Request) -> web.Response:
        """GET /recoveryz: the device-failure recovery surface — the
        SERVING/QUARANTINED/REINIT/REPLAY state machine, quarantine/
        reinit/replay/bisection counters, the last cycle's trigger +
        duration (MTTR evidence), and the transition-event history.
        `{"enabled": false}` when no controller is armed ([recovery]
        enabled=false), so probes need no config knowledge."""
        stats = self.impl.recovery_stats()
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def meshz(self, request: web.Request) -> web.Response:
        """GET /meshz: the mesh serving-mode surface — mesh geometry +
        device list, executor batch/pad counters, the layout source per
        served model, per-device occupancy attribution when the
        utilization ledger rides along, and (elastic mode, ISSUE 15) the
        `elastic` block: current split, ladder, switch history ring,
        per-split serve counters, controller state. `{"enabled": false}`
        when serving is single-chip, so probes need no config
        knowledge."""
        stats = self.impl.mesh_stats()
        return web.json_response(
            stats if stats is not None else {"enabled": False}
        )

    async def cachez(self, request: web.Request) -> web.Response:
        """GET /cachez: the score-cache introspection surface — aggregate +
        per-model hit/miss/coalesced/eviction/expiration counters, hit
        rate, entry/byte occupancy, and the active config, plus a
        `row_cache` block (per-row counters, rows_executed vs
        rows_requested) when the row-granular tier is armed. `{"enabled":
        false}` when no cache is armed (the route always answers, so
        probes need no config knowledge)."""
        stats = self.impl.cache_stats()
        row = self.impl.row_cache_stats()
        if row is not None:
            stats = dict(stats) if stats is not None else {"enabled": False}
            stats["row_cache"] = row
        return web.json_response(stats if stats is not None else {"enabled": False})

    async def cachez_flush(self, request: web.Request) -> web.Response:
        """POST /cachez/flush[?model=NAME]: drop every cached score (or one
        model's). The flush is generation-bumped, so results filled by
        computations already in flight are dropped too."""
        try:
            dropped = self.impl.cache_flush(request.query.get("model") or None)
        except ServiceError as e:
            return _json_error(e.code, str(e))
        return web.json_response({"flushed": True, "entries_dropped": dropped})

    async def status(self, request: web.Request) -> web.Response:
        # ONE status implementation: delegate to the ModelService RPC body
        # (impl.get_model_status) and translate to TF-Serving's REST JSON —
        # the gRPC and REST surfaces cannot drift (and the /versions and
        # /labels pinning arrives for free).
        model = request.match_info["model"]
        try:
            req = apis.GetModelStatusRequest()
            self._fill_model_spec(
                req.model_spec,
                model,
                self._parse_version(request.match_info.get("version")),
                request.match_info.get("label"),
            )
            resp = self.impl.get_model_status(req)
        except ServiceError as e:
            return _json_error(e.code, str(e))
        except ValueError as e:
            # e.g. a /versions/{v} segment past int64: client error, same
            # JSON taxonomy as every other route.
            return _json_error("INVALID_ARGUMENT", str(e))
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            log.exception("internal error serving REST status")
            return _json_error("INTERNAL", f"internal error: {e}")
        state_name = apis.ModelVersionStatus.State.Name
        return web.json_response({
            "model_version_status": [
                {
                    "version": str(s.version),
                    "state": state_name(s.state),
                    # proto3-JSON enum-name convention, like the metadata
                    # route's dtypes: ecosystem parsers match "OK".
                    "status": {
                        "error_code": (
                            "OK" if s.status.error_code == 0
                            else s.status.error_code
                        ),
                        "error_message": s.status.error_message,
                    },
                }
                for s in resp.model_version_status
            ]
        })

    async def metadata(self, request: web.Request) -> web.Response:
        model = request.match_info["model"]
        try:
            # Servable resolution ONLY — no signature lookup: this route
            # enumerates ALL signatures, and a model serving purely by
            # explicit signature names (no serving_default — a supported
            # import shape, interop/savedmodel.py) must still answer.
            from .service import _wrap_lookup

            servable = _wrap_lookup(
                lambda: self.impl.registry.resolve(
                    model,
                    self._parse_version(request.match_info.get("version")),
                    request.match_info.get("label"),
                )
            )
        except ServiceError as e:
            return _json_error(e.code, str(e))
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            log.exception("internal error serving REST metadata")
            return _json_error("INTERNAL", f"internal error: {e}")

        from ..proto import tf_framework_pb2 as fw

        def spec_json(spec):
            shape = (
                {"unknown_rank": True}
                if spec.shape is None
                else {"dim": [{"size": str(-1 if d is None else d)} for d in spec.shape]}
            )
            # Enum by NAME: proto3 JSON (what tensorflow_model_server's
            # REST metadata emits) prints enums as strings, and ecosystem
            # parsers match on "DT_INT64", not 9.
            try:
                dtype = fw.DataType.Name(spec.dtype)
            except ValueError:
                dtype = int(spec.dtype)
            return {"dtype": dtype, "tensor_shape": shape}

        sig_defs = {
            name: {
                "method_name": sig.method_name,
                "inputs": {s.name: spec_json(s) for s in sig.inputs},
                "outputs": {s.name: spec_json(s) for s in sig.outputs},
            }
            for name, sig in servable.signatures.items()
        }
        return web.json_response({
            "model_spec": {
                "name": servable.name,
                "version": str(servable.version),
                "signature_name": "",
            },
            "metadata": {"signature_def": {"signature_def": sig_defs}},
        })


async def start_rest_gateway(
    impl: PredictionServiceImpl,
    host: str = "127.0.0.1",
    port: int = 8501,
    metrics=None,
) -> tuple[web.AppRunner, int]:
    """Start the gateway; returns (runner, bound_port). Stop with
    `await runner.cleanup()`. Pass the gRPC server's ServerMetrics so
    /monitoring/prometheus/metrics aggregates both surfaces."""
    gw = RestGateway(impl, metrics)
    runner = web.AppRunner(gw.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    bound = runner.addresses[0][1]  # public API (private site._server breaks across aiohttp versions)
    return runner, bound
