"""Device-utilization attribution plane: occupancy ledger, gap waterfall,
on-demand deep capture.

The salvaged TPU bench (BENCH_r04/r05) says the chip could sustain ~43k
QPS (MFU 0.70) while the served path achieves ~1% of it
(`achieved_fraction_of_device_limit: 0.011`) — but that number exists only
as an offline bench artifact, and the aggregate `phases_us` sums cannot
say *when* the device sat idle or *why*. ROADMAP item 1 (close the 100x
gap) needs a live, continuously-served decomposition of wall time before
the serving-path overhaul can be driven by data; "Scaling TensorFlow to
300 million predictions per second" (PAPERS.md) finds its batching and
transport amortization wins by attributing exactly this idle time.

Three layers, all off by default and armed by the `[utilization]` config
section (one attribute read per batcher hot-path hook when off — the
tracing/cache/overload precedent):

- **OccupancyLedger**: per-device busy/idle timeline fed by the batcher's
  EXISTING dispatch/jitcall/readback phase sites — ONE interval append
  per completed batch (`note_batch`), ring-bounded, injectable clock.
  Each batch contributes a (stage-start, readback-issued, readback-done)
  triple, so the busy union splits into host-dispatch/H2D, device
  compute, and D2H wait. The idle time BETWEEN busy intervals is
  attributed to its blocking cause from cheap wait-interval records the
  batcher leaves while it idles: `queue_empty` (no work arrived — on
  this rig, the transport/client-bound share), `host_pack` (the host was
  assembling/coalescing while the device starved), `readback_wait`
  (pipeline saturated behind in-flight readbacks), `admission_shed`
  (traffic existed but admission refused it). An in-flight
  pipeline-depth gauge (`in_flight`/`max_in_flight`) rides the same
  hooks.
- **Gap waterfall**: a windowed decomposition of wall time into
  device / h2d_dispatch / d2h / idle-by-cause / other components whose
  sum equals the window's wall time BY CONSTRUCTION (the residual is
  reported as `other`, never hidden), plus a live
  `achieved_fraction_of_device_limit` estimate — calibrated against the
  bench's `device_step_us` table when one is provided (per-bucket pure
  device step x batches served), busy-fraction otherwise (labeled).
  Served as `GET /utilz`, a `utilization` block in `/monitoring`,
  `dts_tpu_utilization_*` Prometheus series, and a per-device counter
  track in the `/tracez?format=chrome` Perfetto export.
- **On-demand deep capture**: `POST /profilez/start?seconds=N` runs a
  `jax.profiler.trace` capture (CPU-safe; artifact dir returned;
  concurrent captures refused with 409) and simultaneously samples every
  host thread's Python stack (the tools/profile_host.py methodology,
  shared here as HostStackSampler) so one call captures the device and
  host sides of the same window together.

The ledger is jax-free; only ProfilerCapture imports jax, lazily, when a
capture actually starts.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

# Idle-gap blocking causes, in reporting order.
GAP_CAUSES = ("queue_empty", "host_pack", "readback_wait", "admission_shed")

# Gap-length histogram edges (milliseconds, cumulative-le semantics).
_GAP_LE_MS = (1.0, 10.0, 100.0, 1000.0)


def _clamp(t0: float, t1: float, w0: float, w1: float) -> float:
    """Length of (t0, t1) ∩ (w0, w1)."""
    return max(0.0, min(t1, w1) - max(t0, w0))


def _merge_intervals(spans: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted union of possibly-overlapping (t0, t1) spans."""
    if not spans:
        return []
    spans = sorted(spans)
    out = [spans[0]]
    for t0, t1 in spans[1:]:
        if t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _overlap_with_union(union: list[tuple[float, float]], t0: float, t1: float) -> float:
    """Seconds of (t0, t1) covered by a sorted disjoint union."""
    total = 0.0
    for u0, u1 in union:
        if u0 >= t1:
            break
        total += _clamp(u0, u1, t0, t1)
    return total


def _normalize_step_table(table: dict | None) -> dict[int, float]:
    """ONE normalization of a per-bucket device-step table: accepts
    {bucket: us} or the envelope's {bucket: [lo, hi]} (midpoint); skips
    non-positive entries (a 0.0 step can only divide-by-zero downstream).
    Shared by load_calibration and set_calibration so the two install
    paths can never disagree on the same artifact."""
    out: dict[int, float] = {}
    for bucket, val in (table or {}).items():
        if isinstance(val, (list, tuple)) and len(val) == 2:
            val = (float(val[0]) + float(val[1])) / 2.0
        if val and float(val) > 0:
            out[int(bucket)] = float(val)
    return out


def load_calibration(path: str) -> dict[int, float]:
    """Per-bucket pure device step (us) from a bench artifact: either the
    healthy-weather envelope (`device_step_us: {bucket: [lo, hi]}` —
    midpoint used) or a measured table (`{bucket: us}`). Empty dict on
    any trouble — calibration is an enrichment, never a dependency."""
    try:
        with open(path) as f:
            doc = json.load(f)
        table = doc.get("device_step_us", doc) if isinstance(doc, dict) else {}
        return _normalize_step_table(table)
    except Exception:  # noqa: BLE001 — absent/corrupt table = no calibration
        return {}


def _split_span(
    waits, open_waits, sheds, g0: float, g1: float,
    residual_to_host_pack: bool = True,
) -> dict[str, float]:
    """Per-cause seconds for the idle span (g0, g1): overlap with the
    recorded wait intervals (open waits count their elapsed part),
    residual to host_pack (optional — startup/in-flight tails leave their
    residual unattributed), queue_empty share reassigned to
    admission_shed when sheds fired inside the span. Pure function over
    the passed collections, so callers can use live rings (under the
    ledger lock) or snapshots (outside it) identically."""
    split = {c: 0.0 for c in GAP_CAUSES}
    # Closed waits are append-ordered by end time: scan from the right
    # and stop once waits end before the gap starts.
    for cause, w0, w1 in reversed(waits):
        if w1 <= g0:
            break
        split[cause] += _clamp(w0, w1, g0, g1)
    for cause, w0 in open_waits:
        split[cause] += _clamp(w0, g1, g0, g1)
    gap = g1 - g0
    explained = sum(split.values())
    if explained > gap > 0:
        # Concurrent waits (coalesce fill + free-ride) can overlap;
        # scale so attribution never exceeds the gap itself.
        scale = gap / explained
        split = {c: s * scale for c, s in split.items()}
        explained = gap
    if residual_to_host_pack:
        split["host_pack"] += max(0.0, gap - explained)
    if split["queue_empty"] > 0 and any(g0 <= t <= g1 for t in sheds):
        split["admission_shed"] += split["queue_empty"]
        split["queue_empty"] = 0.0
    return split


class OccupancyLedger:
    """Busy/idle timeline + idle-gap attribution for one device.

    Hot-path feeders (the batcher, armed only):
    - ``wait_begin(cause)`` / ``wait_end(token)`` around the batcher's
      idle waits (queue-empty block, coalesce fill, pipeline free-ride) —
      two clock reads per wait, paid only while the device is idle
      anyway;
    - ``note_shed()`` at every admission refusal (point event);
    - ``depth_inc()`` / ``depth_dec()`` around each batch's
      dispatch->readback life (the pipeline-depth gauge);
    - ``note_batch(stage_t0, issue_t0, done_t, bucket, candidates,
      d2h_wait_s)`` ONCE per completed batch, from the completer — the
      single interval append the plane is built on.

    Idle-gap attribution: when a batch's busy interval opens a gap after
    the previous busy union, the gap's seconds are split across causes by
    overlap with the recorded wait intervals; the unexplained residual is
    ``host_pack`` (the host was doing per-batch work — pad/pack/digest —
    whenever it was neither waiting nor dispatching). A gap containing
    admission-shed events moves its queue_empty share to
    ``admission_shed``: the queue was empty because traffic was refused,
    not absent. Each gap lands in a per-cause histogram under its
    dominant (largest-share) cause.

    Everything is ring-bounded (``ring`` batches/gaps/waits) and clocked
    by an injectable ``clock`` so tests drive it deterministically.
    """

    def __init__(
        self,
        device: str | None = None,
        ring: int = 4096,
        clock=time.perf_counter,
        calibration: dict[int, float] | None = None,
        window_s: float = 60.0,
    ):
        self.device = device or "device:0"
        # Mesh serving mode (ISSUE 13): the per-chip device list when
        # the ledger attributes a MESH's occupancy. SPMD batches occupy
        # every chip simultaneously, so each listed device carries the
        # same busy timeline — snapshot() adds a per_device block and
        # the Perfetto export emits one counter track per chip. None =
        # single-device (the historical surface, unchanged).
        self.devices: list[str] | None = None
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._started_t = clock()
        # (stage_t0, issue_t0, done_t, bucket, candidates, d2h_wait_s)
        self._ring: deque[tuple] = deque(maxlen=ring)
        # (g0, g1, dominant_cause, per-cause seconds tuple aligned with
        # GAP_CAUSES)
        self._gaps: deque[tuple] = deque(maxlen=ring)
        # (cause, w0, w1) closed wait intervals, append-ordered by w1.
        self._waits: deque[tuple] = deque(maxlen=ring)
        self._open_waits: dict[int, tuple[str, float]] = {}
        self._wait_seq = 0
        self._sheds: deque[float] = deque(maxlen=ring)
        self._busy_until: float | None = None
        # Lifetime counters (ring-independent).
        self.batches = 0
        self.candidates = 0
        self.busy_s = 0.0
        self.gap_s = {c: 0.0 for c in GAP_CAUSES}
        self.gap_counts = {c: 0 for c in GAP_CAUSES}
        self._gap_hist = {c: [0] * (len(_GAP_LE_MS) + 1) for c in GAP_CAUSES}
        self.in_flight = 0
        self.max_in_flight = 0
        self.sheds = 0
        self._calibration = dict(calibration or {})

    # ------------------------------------------------------------- feeders

    def wait_begin(self, cause: str) -> int:
        now = self._clock()
        with self._lock:
            self._wait_seq += 1
            token = self._wait_seq
            self._open_waits[token] = (cause, now)
        return token

    def wait_end(self, token: int) -> None:
        now = self._clock()
        with self._lock:
            entry = self._open_waits.pop(token, None)
            if entry is not None:
                self._waits.append((entry[0], entry[1], now))

    def note_shed(self) -> None:
        now = self._clock()
        with self._lock:
            self.sheds += 1
            self._sheds.append(now)

    def depth_inc(self) -> None:
        with self._lock:
            self.in_flight += 1
            self.max_in_flight = max(self.max_in_flight, self.in_flight)

    def depth_dec(self) -> None:
        with self._lock:
            self.in_flight = max(0, self.in_flight - 1)

    def set_calibration(self, table: dict) -> None:
        """Install/refresh the per-bucket device-step table (us). Accepts
        {bucket: us} or the envelope's {bucket: [lo, hi]} form;
        non-positive values are skipped (same normalizer as
        load_calibration)."""
        clean = _normalize_step_table(table)
        with self._lock:
            self._calibration = clean

    def note_batch(
        self,
        stage_t0: float,
        issue_t0: float,
        done_t: float,
        bucket: int = 0,
        candidates: int = 0,
        d2h_wait_s: float = 0.0,
    ) -> None:
        """ONE interval append per completed batch (from the completer):
        closes the idle gap since the previous busy union, extends the
        union, and records the batch for the windowed waterfall."""
        with self._lock:
            self.batches += 1
            self.candidates += int(candidates)
            first = self._busy_until is None
            prev_end = self._busy_until if not first else self._started_t
            if stage_t0 > prev_end:
                # The span before the FIRST batch is startup, not an
                # attributable idle gap: only its wait-explained share is
                # recorded (the waterfall's `other` residual carries the
                # rest); between-batch gaps charge their residual to
                # host_pack (the host was doing per-batch work whenever
                # it was neither waiting nor dispatching).
                self._close_gap_locked(
                    prev_end, stage_t0, residual_to_host_pack=not first
                )
            self.busy_s += max(0.0, done_t - max(stage_t0, prev_end))
            self._busy_until = max(prev_end, done_t)
            self._ring.append(
                (stage_t0, issue_t0, done_t, int(bucket), int(candidates),
                 max(0.0, float(d2h_wait_s)))
            )

    # ----------------------------------------------------- gap attribution

    def _close_gap_locked(
        self, g0: float, g1: float, residual_to_host_pack: bool = True
    ) -> None:
        split = _split_span(
            self._waits, self._open_waits.values(), self._sheds,
            g0, g1, residual_to_host_pack,
        )
        attributed = sum(split.values())
        if attributed <= 0:
            return  # fully-unattributed startup span: waterfall `other`
        dominant = max(GAP_CAUSES, key=lambda c: split[c])
        self.gap_s[dominant] += attributed
        self.gap_counts[dominant] += 1
        hist = self._gap_hist[dominant]
        gap_ms = attributed * 1e3
        for i, le in enumerate(_GAP_LE_MS):
            if gap_ms <= le:
                hist[i] += 1
                break
        else:
            hist[-1] += 1
        self._gaps.append(
            (g0, g1, dominant, tuple(split[c] for c in GAP_CAUSES))
        )

    # ------------------------------------------------------------- readers

    def waterfall(self, window_s: float | None = None) -> dict:
        """Windowed wall-time decomposition. Components sum to the
        window's wall time by construction: wall = busy (split into
        h2d_dispatch / device / d2h) + per-cause idle + `other` (idle the
        ring no longer covers, e.g. pre-first-batch time) — the residual
        is REPORTED, never folded into a real component."""
        now = self._clock()
        # Snapshot under the lock, compute OUTSIDE it: the same lock
        # serializes the batcher/completer hot-path hooks, and a
        # Prometheus scrape must not stall serving for an
        # O(ring log ring) merge (the chrome_counter_events pattern).
        with self._lock:
            window = float(window_s if window_s is not None else self.window_s)
            ring = list(self._ring)
            gaps = list(self._gaps)
            waits = list(self._waits)
            open_waits = list(self._open_waits.values())
            sheds = list(self._sheds)
            busy_until = self._busy_until
            started_t = self._started_t
            calibration = self._calibration
            in_flight = self.in_flight
        w0 = max(now - window, started_t)
        wall = max(now - w0, 1e-9)
        batches = [b for b in ring if b[2] > w0]
        busy_union = _merge_intervals(
            [(max(b[0], w0), min(b[2], now)) for b in batches
             if min(b[2], now) > max(b[0], w0)]
        )
        busy = sum(t1 - t0 for t0, t1 in busy_union)
        # Busy sub-split: host-dispatch/H2D (stage start -> readback
        # issued) and D2H wait (the completer's measured blocked
        # fetch); device compute is the remainder of the busy union.
        dispatch_raw = sum(
            _clamp(b[0], min(b[1], b[2]), w0, now) for b in batches
        )
        d2h_raw = sum(
            min(b[5], _clamp(b[0], b[2], w0, now)) for b in batches
        )
        sub = dispatch_raw + d2h_raw
        if sub > busy > 0:
            # Pipelined batches overlap, so per-batch sub-spans can
            # exceed the union: scale into it.
            dispatch_raw *= busy / sub
            d2h_raw *= busy / sub
        device = max(0.0, busy - dispatch_raw - d2h_raw)
        idle = {c: 0.0 for c in GAP_CAUSES}
        for g0, g1, _dom, split in gaps:
            full = g1 - g0
            if g1 <= w0 or full <= 0:
                continue
            vis = _clamp(g0, g1, w0, now)
            # Out-of-order completions can retroactively cover a
            # recorded gap: only the still-idle part counts.
            vis -= _overlap_with_union(busy_union, max(g0, w0), min(g1, now))
            if vis <= 0:
                continue
            frac = vis / full
            for c, s in zip(GAP_CAUSES, split):
                idle[c] += s * frac
        # Live tail since the last completed batch: residual idle goes to
        # host_pack only when that is what it means — after at least one
        # batch completed (pre-first-batch time is startup, matching
        # note_batch's exemption) and with nothing in flight (an
        # executing batch's span is busy-in-waiting, not host work; it
        # stays `other` until its completion records it as busy).
        tail0 = max(busy_until if busy_until is not None else started_t, w0)
        if now > tail0:
            tail_split = _split_span(
                waits, open_waits, sheds, tail0, now,
                residual_to_host_pack=(
                    busy_until is not None and in_flight == 0
                ),
            )
            for c, s in tail_split.items():
                idle[c] += s
        other = max(0.0, wall - busy - sum(idle.values()))
        components = {
            "device": device,
            "h2d_dispatch": dispatch_raw,
            "d2h": d2h_raw,
            **{f"idle_{c}": idle[c] for c in GAP_CAUSES},
            "other": other,
        }
        total = sum(components.values())
        # Calibrated device-limit fraction: pure per-bucket device
        # step x batches served in the window, over wall — the live
        # counterpart of the bench's achieved_fraction_of_device_limit.
        calibrated = None
        if calibration:
            est = sum(calibration.get(b[3], 0.0) for b in batches) / 1e6
            calibrated = est / wall
        busy_fraction = busy / wall
        return {
            "window_s": round(window, 3),
            "wall_s": round(wall, 6),
            "components_s": {k: round(v, 6) for k, v in components.items()},
            "sum_s": round(total, 6),
            "sum_over_wall": round(total / wall, 6),
            "busy_fraction": round(busy_fraction, 6),
            "batches": len(batches),
            "achieved_fraction_of_device_limit": round(
                calibrated if calibrated is not None else busy_fraction, 6
            ),
            "calibration": (
                "device_step_table" if calibrated is not None
                else "busy_fraction"
            ),
        }

    def snapshot(self, window_s: float | None = None) -> dict:
        wf = self.waterfall(window_s)
        per_device = (
            {
                d: {"busy_fraction": wf["busy_fraction"]}
                for d in self.devices
            }
            if self.devices else None
        )
        with self._lock:
            gaps = {
                c: {
                    "count": self.gap_counts[c],
                    "total_s": round(self.gap_s[c], 6),
                    "le_ms": dict(
                        zip([str(le) for le in _GAP_LE_MS] + ["+Inf"],
                            self._gap_hist[c])
                    ),
                }
                for c in GAP_CAUSES
            }
            out = {
                "enabled": True,
                "device": self.device,
                "in_flight": self.in_flight,
                "max_in_flight": self.max_in_flight,
                "batches": self.batches,
                "candidates": self.candidates,
                "busy_s": round(self.busy_s, 6),
                "sheds": self.sheds,
                "calibrated": bool(self._calibration),
                "idle_gaps": gaps,
                "waterfall": wf,
            }
        if per_device is not None:
            out["devices"] = list(self.devices)
            out["per_device"] = per_device
            out["occupancy_attribution"] = "spmd_uniform"
        return out

    def chrome_counter_events(self, t_base: float, pid: int) -> list[dict]:
        """Per-device counter track for the Perfetto export: an
        `occupancy` counter stepping with the number of batches in the
        device pipeline, reconstructed from the interval ring. Events are
        emitted in non-decreasing ts order on one named per-device
        track."""
        with self._lock:
            batches = list(self._ring)
        edges: list[tuple[float, int]] = []
        for b in batches:
            edges.append((b[0], +1))
            edges.append((b[2], -1))
        edges.sort()
        # Mesh mode: one counter track per chip (SPMD batches occupy all
        # of them, so every track carries the same edge stream, named
        # after its device); single-device mode keeps the one track.
        tracks = list(self.devices) if self.devices else [self.device]
        events: list[dict] = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": "device-utilization"}},
        ]
        for tid, name in enumerate(tracks):
            events.append(
                {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                 "args": {"name": name}}
            )
        depth = 0
        last_ts = 0
        for t, step in edges:
            depth += step
            ts = max(last_ts, max(0, int((t - t_base) * 1e6)))
            last_ts = ts
            for tid in range(len(tracks)):
                events.append({
                    "ph": "C", "name": "occupancy", "pid": pid, "tid": tid,
                    "ts": ts, "args": {"in_flight": depth},
                })
        return events


# --------------------------------------------------------------------------
# On-demand deep capture: jax.profiler device trace + host stack sampling.


class HostStackSampler:
    """Periodic Python-stack sampler over every live thread — the
    tools/profile_host.py host-side methodology packaged for on-demand
    capture. Aggregates collapsed stacks (``func (file:line);...``) per
    thread name; the report is a plain dict the REST surface serializes.
    Pure stdlib; sampling cost is bounded by interval_s and stack depth."""

    def __init__(self, interval_s: float = 0.02, max_depth: int = 12):
        self.interval_s = max(float(interval_s), 0.001)
        self.max_depth = int(max_depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._counts: dict[tuple[str, str], int] = {}
        self.samples = 0

    def _collapse(self, frame) -> str:
        parts = []
        depth = 0
        while frame is not None and depth < self.max_depth:
            code = frame.f_code
            parts.append(
                f"{code.co_name} ({os.path.basename(code.co_filename)}:{frame.f_lineno})"
            )
            frame = frame.f_back
            depth += 1
        return ";".join(parts)

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.is_set():
            names = {t.ident: t.name for t in threading.enumerate()}
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                key = (names.get(ident, f"thread-{ident}"), self._collapse(frame))
                self._counts[key] = self._counts.get(key, 0) + 1
            self.samples += 1
            self._stop.wait(self.interval_s)

    def start(self) -> "HostStackSampler":
        self._thread = threading.Thread(
            target=self._loop, name="host-stack-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        threads: dict[str, list] = {}
        for (name, stack), count in sorted(
            self._counts.items(), key=lambda kv: -kv[1]
        ):
            threads.setdefault(name, []).append(
                {"stack": stack, "count": count}
            )
        return {
            "samples": self.samples,
            "interval_s": self.interval_s,
            "threads": threads,
        }


class CaptureInProgressError(RuntimeError):
    """A deep capture is already running; concurrent jax.profiler traces
    are refused (the profiler is process-global)."""


class ProfilerCapture:
    """One-at-a-time deep capture: a `jax.profiler.trace` of the device
    side plus a HostStackSampler of the host side, over the same window.
    `start(seconds)` returns immediately with the artifact paths; a
    daemon timer stops both and writes `host_stacks.json` into the
    artifact dir. CPU-safe: a jax profiler that cannot start (headless
    CPU builds, missing plugin) is recorded as `device_trace_error` and
    the host side still captures. Injectable device start/stop hooks keep
    tests deterministic and jax-free."""

    MAX_SECONDS = 120.0

    def __init__(self, base_dir: str | None = None,
                 device_start=None, device_stop=None):
        self.base_dir = base_dir
        self._device_start = device_start
        self._device_stop = device_stop
        self._lock = threading.Lock()
        self._active: dict | None = None

    def _jax_start(self, log_dir: str) -> None:
        import jax

        jax.profiler.start_trace(log_dir)

    def _jax_stop(self) -> None:
        import jax

        jax.profiler.stop_trace()

    def status(self) -> dict:
        with self._lock:
            if self._active is None:
                return {"active": False}
            return {"active": True, **self._active}

    def start(self, seconds: float, host_interval_s: float = 0.02) -> dict:
        import tempfile

        seconds = min(max(float(seconds), 0.05), self.MAX_SECONDS)
        with self._lock:
            if self._active is not None:
                raise CaptureInProgressError(
                    "a profiler capture is already running "
                    f"({self._active.get('artifact_dir')})"
                )
            base = self.base_dir or os.path.join(
                tempfile.gettempdir(), "dts_tpu_profiles"
            )
            os.makedirs(base, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            artifact_dir = tempfile.mkdtemp(
                prefix=f"capture-{stamp}-", dir=base
            )
            info: dict = {
                "artifact_dir": artifact_dir,
                "seconds": seconds,
                "host_stacks": os.path.join(artifact_dir, "host_stacks.json"),
            }
            try:
                (self._device_start or self._jax_start)(artifact_dir)
                info["device_trace"] = True
            except Exception as exc:  # noqa: BLE001 — host side still captures
                info["device_trace"] = False
                info["device_trace_error"] = f"{type(exc).__name__}: {exc}"[:300]
            sampler = HostStackSampler(interval_s=host_interval_s).start()
            self._active = dict(info)

        def finish():
            time.sleep(seconds)
            report = sampler.stop()
            if info.get("device_trace"):
                try:
                    (self._device_stop or self._jax_stop)()
                except Exception as exc:  # noqa: BLE001 — record, release slot
                    info["device_trace_error"] = (
                        f"{type(exc).__name__}: {exc}"[:300]
                    )
            try:
                with open(info["host_stacks"], "w") as f:
                    json.dump(report, f, indent=1)
            except OSError:
                pass
            with self._lock:
                self._active = None

        threading.Thread(target=finish, name="profilez", daemon=True).start()
        return info


# Process-global capture slot (the jax profiler itself is process-global,
# so two REST gateways in one process must share the refusal).
_CAPTURE = ProfilerCapture()


def profiler_capture() -> ProfilerCapture:
    return _CAPTURE
