"""Multi-host serving entry point: one logical model over a multi-process
mesh, operable like the reference's deployment.

The reference ran three independently-started backends behind a client
scatter (DCNClient.java:38); this is the equivalent operational surface for
the tier the reference never had — a SINGLE model spanning hosts
(parallel/multihost.py): every process runs

    python -m distributed_tf_serving_tpu.serving.multihost_server \
        --model-base-path /shared/models/DCN \
        --coordinator HOST0:7777 --num-processes K --process-id k [--port 9999]

process 0 serves gRPC and leads; the rest follow. Versions live in the
TF-Serving base-path convention on SHARED storage (every process must see
the same directory): the leader's VersionWatcher drives slice-wide RELOAD
hot-swaps; followers load each version through the same path. A dead
process fails the whole slice fast (heartbeat-bounded) — restart the job,
exactly like any SPMD deployment.

Split from serving/server.py so single-host serving never imports
jax.distributed machinery.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

log = logging.getLogger("dts_tpu.multihost_server")

# Serving deployments want dead-process detection in seconds, not the
# preemption-tolerant 100 s default (parallel/multihost.py init_distributed).
HEARTBEAT_TIMEOUT_S = 10


def build_multihost_stack(
    base_path,
    coordinator: str | None,
    num_processes: int,
    process_id: int,
    model_kind: str = "dcn_v2",
    model_name: str = "DCN",
    buckets: tuple[int, ...] = (1024, 8192),
    model_parallel: int = 1,
    max_wait_us: int = 2000,
    poll_interval_s: float = 5.0,
    max_load_attempts: int = 3,
):
    """Initialize the distributed runtime and build the serving stack.

    Returns (runner, registry, batcher, impl, watcher) on process 0 and
    (runner, None, None, None, None) on followers — the caller runs
    `runner.follow()` there. The initial version is chosen by the LEADER
    and broadcast, so processes scanning shared storage at different
    moments cannot disagree about the starting params.

    Model architecture comes from the CHECKPOINT MANIFEST, never from
    flags: the operator cannot re-specify embed_dim/vocab/mlp_dims wrong,
    and the batch templates are derived from the servable's own signature
    (so DLRM's dense_features input is carried, not silently dropped).
    `model_kind` only parameterizes the watcher's SavedModel-dir handling.
    """
    import dataclasses as dc

    from jax.experimental import multihost_utils

    from ..models import ServableRegistry
    from ..parallel.multihost import MultiHostRunner, global_mesh, init_distributed
    from ..train.checkpoint import load_servable
    from .batcher import DynamicBatcher
    from .service import PredictionServiceImpl
    from .version_watcher import VersionWatcher, VersionWatcherConfig, scan_versions

    init_distributed(
        coordinator, num_processes, process_id,
        heartbeat_timeout_s=HEARTBEAT_TIMEOUT_S,
    )
    mesh = global_mesh(model_parallel=model_parallel)

    # Leader picks the starting version; everyone loads that exact one.
    if num_processes > 1:
        local_latest = max(scan_versions(base_path), default=0) if process_id == 0 else 0
        initial = int(
            multihost_utils.broadcast_one_to_all(np.asarray([local_latest], np.int64))[0]
        )
    else:
        initial = max(scan_versions(base_path), default=0)
    if initial == 0:
        raise FileNotFoundError(f"no version directories under {base_path}")

    def load_version(version: int):
        # Host restore: every process reads the full tree; the runner
        # places it at a protocol-aligned point (construction, or _place
        # after the RELOAD header) — a device restore here would need
        # cross-process shardings orbax cannot infer from a single-process
        # checkpoint, and orbax's own restore barrier would interleave
        # with the runner's collectives.
        return load_servable(f"{base_path}/{version}", host=True)

    def filter_signatures(sv, version):
        # The broadcast protocol gathers ONE output tensor (the scores);
        # the registered signature must promise exactly what the runner
        # serves, or Predict without an output_filter would fail INTERNAL
        # ("model produced [...] but signature declares [..., 'logits']").
        signatures = {
            name: dc.replace(
                sig,
                outputs=tuple(s for s in sig.outputs if s.name == "prediction_node"),
            )
            for name, sig in sv.signatures.items()
        }
        return dc.replace(sv, version=version, name=model_name, signatures=signatures)

    initial_sv = filter_signatures(load_version(initial), initial)
    model = initial_sv.model
    config = model.config

    # Templates from the servable's OWN signature: every declared input is
    # carried across the broadcast (feat_ids as post-fold int32; the rest —
    # feat_wts, DLRM dense_features — as float32 with their trailing dims).
    sig = initial_sv.signature("")
    def template(b: int) -> dict[str, np.ndarray]:
        out = {}
        for spec in sig.inputs:
            trailing = tuple(d or 1 for d in (spec.shape or (None, 1))[1:])
            if spec.name == "feat_ids":
                out[spec.name] = np.zeros((b, *trailing), np.int32)
            else:
                out[spec.name] = np.zeros((b, *trailing), np.float32)
        return out

    runner = MultiHostRunner(
        mesh=mesh,
        params=initial_sv.params,
        score_fn=lambda p, b: model.apply(p, b)["prediction_node"],
        batch_templates=[template(b) for b in sorted(buckets)],
        param_loader=lambda version: load_version(version).params,
    )
    runner.version = initial
    if process_id != 0:
        return runner, None, None, None, None

    registry = ServableRegistry()
    # Pre-seed the initial version: the watcher's first poll must not
    # re-restore and re-broadcast what every process just loaded.
    registry.load(initial_sv)
    batcher = DynamicBatcher(
        buckets=runner.buckets, max_wait_us=max_wait_us, run_fn=runner.as_run_fn()
    ).start()
    impl = PredictionServiceImpl(registry, batcher)
    # Label-only reloads may re-state this source verbatim (deploy tools
    # replay their full config to flip a label); without this entry the
    # single-model reload gate reads the re-statement as a base-path MOVE
    # and rejects it FAILED_PRECONDITION — same wiring as build_stack's
    # --model-base-path mode.
    impl.served_sources[model_name] = (str(base_path), model_kind)

    watcher = VersionWatcher(
        base_path,
        registry,
        VersionWatcherConfig(
            poll_interval_s=poll_interval_s,
            model_name=model_name,
            model_kind=model_kind,
            max_load_attempts=max_load_attempts,
        ),
        loader=runner.watcher_loader(
            lambda version, path: filter_signatures(load_servable(path, host=True), version)
        ),
    ).start()
    return runner, registry, batcher, impl, watcher


def serve(argv=None) -> None:
    import argparse
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Honor an explicit CPU request over this image's sitecustomize
        # accelerator pin (config-level override required before backend
        # init — same guard as the single-host CLI, serving/server.py).
        jax.config.update("jax_platforms", "cpu")

    from .server import create_server

    parser = argparse.ArgumentParser(description="Multi-host TPU PredictionService")
    parser.add_argument("--model-base-path", required=True)
    parser.add_argument("--coordinator", help="process-0 address host:port (jax.distributed)")
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--port", type=int, default=9999)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--model-kind", default="dcn_v2",
                        help="only for SavedModel version dirs; native "
                        "checkpoints carry their architecture in the manifest")
    parser.add_argument("--model-name", default="DCN")
    parser.add_argument("--buckets", default="1024,8192",
                        help="comma-separated multihost bucket ladder")
    parser.add_argument("--model-parallel", type=int, default=1)
    parser.add_argument("--max-workers", type=int, default=32)
    parser.add_argument("--rest-port", type=int, default=0,
                        help="leader also serves the TF-Serving REST API "
                        "(:8501 surface) on this port")
    parser.add_argument("--ssl-config-file", dest="ssl_config_file",
                        help="secure the leader's gRPC port (SSLConfig "
                        "textproto, same format as the single-host CLI)")
    parser.add_argument("--file-system-poll-wait-seconds",
                        dest="file_system_poll_wait_seconds", type=float,
                        default=5.0,
                        help="version-watcher poll interval (upstream flag name)")
    parser.add_argument("--max-num-load-retries", dest="max_num_load_retries",
                        type=int, default=2,
                        help="retries AFTER the first load attempt "
                        "(upstream flag semantics)")
    args = parser.parse_args(argv)
    # Fail-fast like the single-host CLI: validate before slice init.
    credentials = None
    if args.ssl_config_file:
        from .server import load_ssl_credentials

        credentials = load_ssl_credentials(args.ssl_config_file)

    logging.basicConfig(level=logging.INFO)
    runner, registry, batcher, impl, watcher = build_multihost_stack(
        args.model_base_path,
        args.coordinator,
        args.num_processes,
        args.process_id,
        model_kind=args.model_kind,
        model_name=args.model_name,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        model_parallel=args.model_parallel,
        poll_interval_s=args.file_system_poll_wait_seconds,
        max_load_attempts=args.max_num_load_retries + 1,  # upstream: retries
    )
    if args.process_id != 0:
        log.info("follower %d/%d up (mesh %s); serving until leader shutdown",
                 args.process_id, args.num_processes, dict(runner.mesh.shape))
        runner.follow()
        log.info("follower %d released", args.process_id)
        return

    from ..utils.metrics import ServerMetrics

    # ONE metrics instance across gRPC and REST (the monitoring-endpoint
    # aggregation contract, same as the single-host CLI).
    metrics = ServerMetrics()
    # create_server registers grpc.health.v1 alongside Prediction/Model
    # services: the leader answers standard health probes (and the fan-out
    # client's half-open probing) with per-model status — the initial
    # version is pre-seeded above, so "" reports SERVING from first bind.
    server, port = create_server(
        impl, f"{args.host}:{args.port}", args.max_workers, metrics,
        credentials=credentials,
    )
    server.start()
    if args.rest_port:
        from .server import start_rest_in_thread

        try:
            bound = start_rest_in_thread(impl, args.host, args.rest_port, metrics)
        except RuntimeError as exc:
            # Same teardown ORDER as the normal path: watcher first, so no
            # RELOAD broadcast can interleave with the slice shutdown.
            watcher.stop()
            server.stop(0)
            batcher.stop()
            runner.shutdown()
            raise SystemExit(str(exc)) from exc
        log.info("REST gateway on %s:%d (/v1/models/...)", args.host, bound)
    log.info("multihost PredictionService on %s:%d (mesh %s, version %s)",
             args.host, port, dict(runner.mesh.shape), runner.version)
    try:
        server.wait_for_termination()
    finally:
        watcher.stop()
        server.stop(2).wait()
        batcher.stop()
        runner.shutdown()


if __name__ == "__main__":
    serve()
