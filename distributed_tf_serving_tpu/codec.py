"""TensorProto <-> numpy array codec.

Implements both wire encodings of the reference's tensor data plane
(tensor.proto:14-84 in the reference's vendored protos): the raw
little-endian `tensor_content` fast path (zero-copy via np.frombuffer) and
the per-dtype repeated fields (the encoding the reference's Java client emits
— int64_val/float_val, DCNClient.java:98-108). Every real dtype in
types.proto:11-67 is covered, including DT_BFLOAT16 (TPU-native) and DT_HALF
via the int32-widened `half_val` bit-pattern field.

Unlike the external tensorflow_model_server the reference talked to, this
codec *validates* element counts against the declared shape — the reference's
smoke client (DCNClientSimple.java:26-51) declares [1500,43] but sends ~2 rows
and the external server accepted it; here that is an explicit CodecError.
"""

from __future__ import annotations

import numpy as np
import ml_dtypes

from .proto import tf_framework_pb2 as fw

DataType = fw.DataType


class CodecError(ValueError):
    """Raised for malformed, inconsistent, or unsupported TensorProtos."""


# DataType -> (numpy dtype, repeated-field name). Quantized dtypes decode to
# their underlying integer layout; DT_STRING is handled separately (ragged
# bytes, no fixed itemsize).
_DTYPES: dict[int, tuple[np.dtype, str]] = {
    DataType.DT_FLOAT: (np.dtype(np.float32), "float_val"),
    DataType.DT_DOUBLE: (np.dtype(np.float64), "double_val"),
    DataType.DT_INT32: (np.dtype(np.int32), "int_val"),
    DataType.DT_UINT8: (np.dtype(np.uint8), "int_val"),
    DataType.DT_INT16: (np.dtype(np.int16), "int_val"),
    DataType.DT_INT8: (np.dtype(np.int8), "int_val"),
    DataType.DT_COMPLEX64: (np.dtype(np.complex64), "scomplex_val"),
    DataType.DT_INT64: (np.dtype(np.int64), "int64_val"),
    DataType.DT_BOOL: (np.dtype(np.bool_), "bool_val"),
    DataType.DT_QINT8: (np.dtype(np.int8), "int_val"),
    DataType.DT_QUINT8: (np.dtype(np.uint8), "int_val"),
    DataType.DT_QINT32: (np.dtype(np.int32), "int_val"),
    DataType.DT_BFLOAT16: (np.dtype(ml_dtypes.bfloat16), "half_val"),
    DataType.DT_QINT16: (np.dtype(np.int16), "int_val"),
    DataType.DT_QUINT16: (np.dtype(np.uint16), "int_val"),
    DataType.DT_UINT16: (np.dtype(np.uint16), "int_val"),
    DataType.DT_COMPLEX128: (np.dtype(np.complex128), "dcomplex_val"),
    DataType.DT_HALF: (np.dtype(np.float16), "half_val"),
    DataType.DT_UINT32: (np.dtype(np.uint32), "uint32_val"),
    DataType.DT_UINT64: (np.dtype(np.uint64), "uint64_val"),
}

# numpy dtype -> DataType, for encoding. bfloat16 first so it wins the lookup.
_NP_TO_DT: dict[np.dtype, int] = {
    np.dtype(ml_dtypes.bfloat16): DataType.DT_BFLOAT16,
    np.dtype(np.float32): DataType.DT_FLOAT,
    np.dtype(np.float64): DataType.DT_DOUBLE,
    np.dtype(np.float16): DataType.DT_HALF,
    np.dtype(np.int64): DataType.DT_INT64,
    np.dtype(np.int32): DataType.DT_INT32,
    np.dtype(np.int16): DataType.DT_INT16,
    np.dtype(np.int8): DataType.DT_INT8,
    np.dtype(np.uint64): DataType.DT_UINT64,
    np.dtype(np.uint32): DataType.DT_UINT32,
    np.dtype(np.uint16): DataType.DT_UINT16,
    np.dtype(np.uint8): DataType.DT_UINT8,
    np.dtype(np.bool_): DataType.DT_BOOL,
    np.dtype(np.complex64): DataType.DT_COMPLEX64,
    np.dtype(np.complex128): DataType.DT_COMPLEX128,
}


# Little-endian (wire byte order) dtype per DataType, precomputed: dtype
# object construction per call is measurable at 500 QPS, and on LE hosts the
# post-frombuffer astype is a no-op against these.
_DTYPES_LE: dict[int, np.dtype] = {
    dt: np_dtype.newbyteorder("<") for dt, (np_dtype, _f) in _DTYPES.items()
}


def dtype_to_numpy(dt: int) -> np.dtype:
    if dt not in _DTYPES:
        raise CodecError(f"unsupported DataType: {DataType.Name(dt) if dt in DataType.values() else dt}")
    return _DTYPES[dt][0]


def numpy_to_dtype(dtype: np.dtype) -> int:
    dtype = np.dtype(dtype)
    if dtype not in _NP_TO_DT:
        raise CodecError(f"no DataType mapping for numpy dtype {dtype}")
    return _NP_TO_DT[dtype]


def shape_from_proto(shape: fw.TensorShapeProto) -> tuple[int, ...]:
    if shape.unknown_rank:
        raise CodecError("unknown_rank shapes are not servable")
    dims = tuple(d.size for d in shape.dim)
    if any(d < 0 for d in dims):
        raise CodecError(f"negative dimension in shape {dims}")
    return dims


def shape_to_proto(shape: tuple[int, ...]) -> fw.TensorShapeProto:
    return fw.TensorShapeProto(dim=[fw.TensorShapeProto.Dim(size=int(s)) for s in shape])


def _num_elements(dims: tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def to_ndarray(tp: fw.TensorProto) -> np.ndarray:
    """Decode a TensorProto to a numpy array, validating shape vs payload."""
    dt = tp.dtype
    dims = shape_from_proto(tp.tensor_shape)
    n = _num_elements(dims)

    if dt == DataType.DT_STRING:
        vals = list(tp.string_val)
        if len(vals) != n:
            raise CodecError(f"DT_STRING: {len(vals)} values for shape {dims} ({n} elements)")
        out = np.empty(n, dtype=object)
        out[:] = vals
        return out.reshape(dims)

    np_dtype, field = _DTYPES.get(dt, (None, None))
    if np_dtype is None:
        raise CodecError(
            f"unsupported DataType: {DataType.Name(dt) if dt in DataType.values() else dt}"
        )

    # Bind ONCE: every upb bytes-field access copies the payload (~9 us per
    # half-MB on this rig); the frombuffer view below aliases this specific
    # bytes object, keeping the decode zero-copy end to end.
    content = tp.tensor_content
    if content:
        buf = np.frombuffer(content, dtype=_DTYPES_LE[dt])
        if buf.size != n:
            raise CodecError(
                f"tensor_content holds {buf.size} {np_dtype} elements, shape {dims} needs {n}"
            )
        return buf.astype(np_dtype, copy=False).reshape(dims)

    vals = getattr(tp, field)
    nvals = len(vals)

    if field == "half_val":
        # uint16 bit patterns widened to int32 on the wire.
        if nvals != n:
            raise CodecError(f"half_val holds {nvals} elements, shape {dims} needs {n}")
        bits = np.asarray(vals, dtype=np.int32).astype(np.uint16)
        return bits.view(np_dtype).reshape(dims)

    if field in ("scomplex_val", "dcomplex_val"):
        # Interleaved (real, imag) pairs.
        if nvals != 2 * n:
            raise CodecError(f"{field} holds {nvals} floats, shape {dims} needs {2 * n}")
        real_dtype = np.float32 if field == "scomplex_val" else np.float64
        flat = np.asarray(vals, dtype=real_dtype)
        return flat.view(np_dtype).reshape(dims)

    if nvals == n:
        return np.asarray(vals, dtype=np_dtype).reshape(dims)
    if nvals == 1 and n >= 1:
        # Proto3 scalar-broadcast convention: a single value fills the tensor.
        return np.full(dims, np.asarray(vals[0], dtype=np_dtype), dtype=np_dtype)
    raise CodecError(f"{field} holds {nvals} elements, shape {dims} needs {n}")


# ------------------------------------------------- int8 score response wire
#
# ISSUE 12: the network twin of the batcher's int8 D2H compaction — a
# client that opts in (x-dts-score-wire: int8 metadata, against a server
# with [kernels] int8_score_wire enabled) receives the score tensor as
# DT_INT8 plus two 1-element DT_FLOAT sidecar outputs carrying the affine
# (scale, min) pair, and dequantizes locally: 4x fewer response bytes per
# score than f32 tensor_content, 2x fewer than a bf16 wire. Same
# 254-level affine scheme as ops/transfer.py (kept numerically identical
# but implemented here in pure numpy — this module must stay jax-free).

Q8_WIRE_LEVELS = 254.0
Q8_WIRE_SCALE_SUFFIX = "/q8_scale"
Q8_WIRE_MIN_SUFFIX = "/q8_min"


def quantize_scores(arr: np.ndarray) -> tuple[np.ndarray, float, float]:
    """Affine int8 quantization of a float score array on host; returns
    (q int8, scale, min). Worst-case dequant error is range/508."""
    v = np.asarray(arr, np.float32)
    mn = float(v.min()) if v.size else 0.0
    mx = float(v.max()) if v.size else 0.0
    scale = max((mx - mn) / Q8_WIRE_LEVELS, 1e-8)
    q = (np.clip(np.rint((v - mn) / scale), 0.0, Q8_WIRE_LEVELS) - 127.0)
    return q.astype(np.int8), scale, mn


def dequantize_scores(q: np.ndarray, scale: float, mn: float) -> np.ndarray:
    """Inverse of quantize_scores (float32)."""
    return (np.asarray(q, np.float32) + 127.0) * float(scale) + float(mn)


def dequantize_response_output(outputs_map, key: str) -> np.ndarray:
    """Client-side decode of one response output that MAY ride the int8
    score wire: a DT_INT8 tensor with its two sidecar outputs present is
    dequantized to float32; anything else decodes normally. `outputs_map`
    is a PredictResponse.outputs protobuf map."""
    tp = outputs_map[key]
    skey, mkey = key + Q8_WIRE_SCALE_SUFFIX, key + Q8_WIRE_MIN_SUFFIX
    if tp.dtype == DataType.DT_INT8 and skey in outputs_map and mkey in outputs_map:
        q = to_ndarray(tp)
        scale = float(to_ndarray(outputs_map[skey])[0])
        mn = float(to_ndarray(outputs_map[mkey])[0])
        return dequantize_scores(q, scale, mn)
    return to_ndarray(tp)


# ---------------------------------------------------- wire integrity (CRC)
#
# ISSUE 20: CRC32C (Castagnoli — the polynomial every storage/RPC stack
# uses for exactly this job) sidecars over tensor bytes, stamped into
# gRPC metadata on both directions so silent wire corruption is DETECTED
# instead of served. Both ends checksum the same canonical form — the
# DECODED ndarray's dtype/shape header + contiguous payload bytes — so
# the check is encoding-independent (tensor_content, repeated fields,
# and the int8 score wire all verify identically). Lives here because
# this module is the one tensor-bytes authority both the client package
# (jax-free) and the server share.

try:  # C-speed when the wheel is present; the table fallback keeps the
    # client package dependency-free (same rationale as staying jax-free).
    import google_crc32c as _crc32c_native
except ImportError:  # pragma: no cover - exercised only without the wheel
    _crc32c_native = None

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (_CRC32C_POLY if _c & 1 else 0)
    _CRC32C_TABLE.append(_c)
del _i, _c

CRC_INPUT_MD = "x-dts-input-crc"
CRC_SCORE_MD = "x-dts-score-crc"


def crc32c(data, crc: int = 0) -> int:
    """CRC32C over a bytes-like; pass a prior value to chain."""
    if _crc32c_native is not None:
        return _crc32c_native.extend(crc, bytes(data))
    crc ^= 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in bytes(data):
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def ndarray_crc(arr: np.ndarray) -> int:
    """Canonical tensor checksum: dtype/shape header chained with the
    contiguous payload bytes, so a flipped shape dim is as detectable as
    a flipped payload bit."""
    a = np.ascontiguousarray(arr)
    head = f"{a.dtype.str}:{a.shape}".encode()
    return crc32c(a.tobytes(), crc32c(head))


def crc_sidecar(arrays: dict) -> str:
    """Encode per-tensor checksums as one metadata value:
    ``name=%08x`` pairs joined by commas, name order sorted so the
    sidecar is deterministic regardless of map iteration order."""
    return ",".join(
        f"{name}={ndarray_crc(arrays[name]):08x}" for name in sorted(arrays)
    )


def parse_crc_sidecar(value: str) -> dict[str, int]:
    """Inverse of crc_sidecar. Malformed entries raise CodecError — a
    corrupted SIDECAR must fail the integrity check, not pass it."""
    out: dict[str, int] = {}
    for pair in filter(None, (p.strip() for p in value.split(","))):
        name, sep, hexcrc = pair.rpartition("=")
        if not sep or not name:
            raise CodecError(f"malformed crc sidecar entry {pair!r}")
        try:
            out[name] = int(hexcrc, 16)
        except ValueError as e:
            raise CodecError(f"malformed crc sidecar entry {pair!r}") from e
    return out


def verify_crc_sidecar(arrays: dict, sidecar: str) -> list[str]:
    """Names whose decoded bytes mismatch their stamped checksum.
    Names stamped but absent from `arrays` are reported too (a dropped
    tensor is corruption); names present but unstamped are NOT (the
    sidecar may cover a subset, e.g. score-only response stamping)."""
    stamped = parse_crc_sidecar(sidecar)
    return sorted(
        name for name, want in stamped.items()
        if name not in arrays or ndarray_crc(arrays[name]) != want
    )


class EncodeArena:
    """Preallocated encode scratch (ISSUE 9 transport satellite).

    The response-encode path allocates transient numpy buffers per call —
    the contiguity copy for a strided tensor, the float32 widen for a
    wire-dtype leak, the dense (n, num_fields) batches the Example decoder
    builds — and at streamed-sub-batch rates those allocations churn the
    allocator for bytes whose lifetime is one encode. An arena hands back
    the SAME backing storage each time, grown geometrically and keyed by
    dtype, so steady-state encode performs zero large allocations.

    NOT thread-safe by design: hold one arena per thread (the service
    keeps a threading.local). Scratch returned by ndarray()/contiguous()/
    widen_f32() is valid only until the next call for the same dtype —
    callers must finish consuming (protobuf copies on field assignment;
    the batcher's prepare_inputs copies writable inputs) before reusing.
    Off by default everywhere ([transport] response_arena = false keeps
    the historical allocate-per-call behavior)."""

    def __init__(self):
        self._bufs: dict[str, bytearray] = {}
        self.reuses = 0
        self.grows = 0

    def ndarray(self, shape: tuple, dtype) -> np.ndarray:
        """A writable scratch array of the requested geometry over reused
        backing storage (contents undefined — callers overwrite fully)."""
        dt = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dt.itemsize
        buf = self._bufs.get(dt.str)
        if buf is None or len(buf) < nbytes:
            # Geometric growth: successive request sizes within 2x reuse
            # one allocation instead of reallocating per high-water mark.
            buf = bytearray(max(nbytes, 2 * len(buf) if buf else 0, 1024))
            self._bufs[dt.str] = buf
            self.grows += 1
        else:
            self.reuses += 1
        return np.frombuffer(buf, dtype=dt, count=int(np.prod(shape))).reshape(shape)

    def contiguous(self, arr: np.ndarray) -> np.ndarray:
        """C-contiguous view of `arr`'s data: the array itself when already
        contiguous, else a copy into arena scratch (what
        np.ascontiguousarray would allocate fresh)."""
        if arr.flags.c_contiguous:
            return arr
        out = self.ndarray(arr.shape, arr.dtype)
        np.copyto(out, arr)
        return out

    def widen_f32(self, arr: np.ndarray) -> np.ndarray:
        """`arr.astype(np.float32)` into arena scratch (the signature-dtype
        widen for half-precision wire leaks)."""
        out = self.ndarray(arr.shape, np.float32)
        np.copyto(out, arr, casting="unsafe")
        return out


def from_ndarray(
    arr: np.ndarray,
    *,
    dtype_enum: int | None = None,
    use_tensor_content: bool = True,
    out: fw.TensorProto | None = None,
    arena: EncodeArena | None = None,
) -> fw.TensorProto:
    """Encode a numpy array as a TensorProto.

    use_tensor_content=True emits the raw-bytes fast path; False emits the
    per-dtype repeated fields (what grpc-java clients typically build).
    dtype_enum overrides the inferred DataType (needed for quantized dtypes,
    which share numpy layouts with plain integers). `out` fills an existing
    (empty) message in place — e.g. a request's map entry — skipping the
    CopyFrom of the encoded bytes (one fewer half-MB copy per request on
    the serving hot path). `arena` (EncodeArena) reuses scratch storage for
    any transient copy this encode needs instead of allocating fresh.
    """
    arr = np.asarray(arr)
    if not arr.flags.c_contiguous:
        # Note: ascontiguousarray would also promote 0-d to 1-d, so only call
        # it when actually needed (0-d arrays are always contiguous).
        arr = (
            arena.contiguous(arr) if arena is not None
            else np.ascontiguousarray(arr)
        )

    if arr.dtype == object or arr.dtype.kind in ("S", "U"):
        tp = out if out is not None else fw.TensorProto()
        tp.dtype = DataType.DT_STRING
        tp.tensor_shape.CopyFrom(shape_to_proto(arr.shape))
        for v in arr.ravel():
            tp.string_val.append(v.encode() if isinstance(v, str) else bytes(v))
        return tp

    dt = dtype_enum if dtype_enum is not None else numpy_to_dtype(arr.dtype)
    np_dtype, field = _DTYPES[dt]
    if np_dtype != arr.dtype:
        raise CodecError(f"array dtype {arr.dtype} does not match {DataType.Name(dt)}")

    tp = out if out is not None else fw.TensorProto()
    tp.dtype = dt
    tp.tensor_shape.CopyFrom(shape_to_proto(arr.shape))
    if use_tensor_content:
        tp.tensor_content = arr.astype(_DTYPES_LE[dt], copy=False).tobytes()
        return tp

    flat = arr.ravel()
    if field == "half_val":
        tp.half_val.extend(flat.view(np.uint16).astype(np.int32).tolist())
    elif field in ("scomplex_val", "dcomplex_val"):
        real_dtype = np.float32 if field == "scomplex_val" else np.float64
        getattr(tp, field).extend(flat.view(real_dtype).tolist())
    else:
        getattr(tp, field).extend(flat.tolist())
    return tp
