"""Client layer: candidate sharding, async fan-out Predict, bench harness."""

from .bench import (
    BenchReport,
    make_payload,
    make_zipfian_payloads,
    run_closed_loop,
    run_closed_loop_mp,
    transfer_counters,
    zipfian_indices,
)
from .client import (
    PredictClientError,
    PredictResult,
    PreparedRequest,
    ResilienceCounters,
    ShardedPredictClient,
    build_predict_request,
    client_from_config,
    compact_payload,
    keepalive_channel_options,
    label_keys,
    predict_sync,
    report_label,
)
from .health import BackendScoreboard, ScoreboardConfig
from .partition import (
    StreamingMerger,
    merge_host_order,
    partition_bounds,
    partition_flat,
    partition_list,
    shard_candidates,
)

__all__ = [
    "ShardedPredictClient",
    "PredictClientError",
    "PredictResult",
    "PreparedRequest",
    "ResilienceCounters",
    "BackendScoreboard",
    "ScoreboardConfig",
    "keepalive_channel_options",
    "build_predict_request",
    "client_from_config",
    "compact_payload",
    "label_keys",
    "report_label",
    "predict_sync",
    "partition_bounds",
    "partition_list",
    "partition_flat",
    "shard_candidates",
    "merge_host_order",
    "StreamingMerger",
    "BenchReport",
    "make_payload",
    "make_zipfian_payloads",
    "run_closed_loop",
    "transfer_counters",
    "zipfian_indices",
]
