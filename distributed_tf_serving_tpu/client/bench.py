"""Closed-loop benchmark harness — the reference's L6 layer, with percentiles.

Reproduces DCNClient.main's methodology (DCNClient.java:205-241): the payload
is built ONCE and re-sent for every request (DCNClient.java:208-210), N
concurrent workers each issue M sequential logical requests
(concurrentNum=6 x requestNum=1000 upstream), every request is wall-clock
timed end to end including the merge+sort, and an aggregate is reported.
The reference prints only the mean (DCNClient.java:234-236); BASELINE.md's
target metric set needs p50/p99 and QPS, so the raw sample list is kept and
summarized here.
"""

from __future__ import annotations

import asyncio
import dataclasses
import math
import multiprocessing as mp
import queue
import time

import numpy as np

from .client import ShardedPredictClient


@dataclasses.dataclass
class BenchReport:
    latencies_ms: np.ndarray
    wall_s: float
    concurrency: int
    requests_per_worker: int
    candidates: int

    @property
    def requests(self) -> int:
        return self.latencies_ms.size

    def percentile(self, q: float) -> float:
        return float(np.percentile(self.latencies_ms, q))

    @property
    def qps(self) -> float:
        return self.requests / self.wall_s

    @property
    def candidates_per_s(self) -> float:
        return self.requests * self.candidates / self.wall_s

    def summary(self) -> dict:
        return {
            "requests": self.requests,
            "concurrency": self.concurrency,
            "candidates_per_request": self.candidates,
            "mean_ms": float(self.latencies_ms.mean()),
            "p50_ms": self.percentile(50),
            "p90_ms": self.percentile(90),
            "p99_ms": self.percentile(99),
            "qps": self.qps,
            "candidates_per_s": self.candidates_per_s,
            "wall_s": self.wall_s,
        }


def transfer_counters(stats) -> dict:
    """D2H attribution block for bench artifacts, from a BatcherStats-like
    object (duck-typed: this client package stays jax/batcher-import-free).
    Pairs the actual wire bytes fetched (post output-compaction dtype, post
    output filter) with the full-fp32 all-outputs baseline, plus how much
    of the in-flight readback window the completer threads actually
    blocked on (1.0 = the transfer hid entirely behind other work)."""
    down = getattr(stats, "bytes_downloaded", 0)
    full = getattr(stats, "bytes_download_full_f32", 0)
    return {
        "bytes_downloaded_mb": round(down / 1e6, 3),
        "bytes_full_f32_mb": round(full / 1e6, 3),
        "bytes_saved_mb": round(max(full - down, 0) / 1e6, 3),
        "compaction_ratio": round(full / down, 2) if down else None,
        "readback_overlap_fraction": round(
            getattr(stats, "readback_overlap_fraction", 0.0), 3
        ),
        "topk_batches": getattr(stats, "topk_batches", 0),
    }


def make_payload(candidates: int = 1500, num_fields: int = 43, seed: int = 7):
    """The reference workload point: [candidateNum, FIELD_NUM] int64 ids +
    float weights (DCNClient.java:25,29,57-74)."""
    rng = np.random.RandomState(seed)
    return {
        "feat_ids": rng.randint(0, 1 << 40, size=(candidates, num_fields)).astype(np.int64),
        "feat_wts": rng.rand(candidates, num_fields).astype(np.float32),
    }


def zipfian_indices(
    n: int, pool_size: int, skew: float = 1.1, seed: int = 0
) -> np.ndarray:
    """Deterministic seeded zipfian index stream: n draws over
    [0, pool_size) with P(i) ∝ 1/(i+1)^skew. The SAME (n, pool_size, skew,
    seed) replays the identical sequence, so cache-on/cache-off A/B runs
    serve the identical request stream — the anti-flattering requirement
    for any cache measurement."""
    if pool_size <= 0:
        raise ValueError(f"pool_size must be positive, got {pool_size}")
    rng = np.random.RandomState(seed)
    p = np.arange(1, pool_size + 1, dtype=np.float64) ** -float(skew)
    p /= p.sum()
    return rng.choice(pool_size, size=n, p=p)


def make_zipfian_payloads(
    pool: int,
    candidates: int,
    num_fields: int = 43,
    skew: float = 1.1,
    seed: int = 0,
    catalog: int = 4096,
) -> list[dict[str, np.ndarray]]:
    """`pool` payloads whose candidate ROWS are drawn zipfian (seeded, so
    deterministic) from a catalog of `catalog` distinct candidate rows —
    the CTR traffic shape the cache plane exists for: hot rows recur
    WITHIN a payload (intra-batch duplicate collapse) and ACROSS payloads,
    while whole-payload repeats (zipfian_indices over this pool) exercise
    the exact-match score cache and single-flight coalescing."""
    rng = np.random.RandomState(seed)
    cat_ids = rng.randint(
        0, 1 << 40, size=(catalog, num_fields)
    ).astype(np.int64)
    cat_wts = rng.rand(catalog, num_fields).astype(np.float32)
    p = np.arange(1, catalog + 1, dtype=np.float64) ** -float(skew)
    p /= p.sum()
    out = []
    for _ in range(pool):
        rows = rng.choice(catalog, size=candidates, p=p)
        out.append({
            "feat_ids": np.ascontiguousarray(cat_ids[rows]),
            "feat_wts": np.ascontiguousarray(cat_wts[rows]),
        })
    return out


async def run_closed_loop(
    client: ShardedPredictClient,
    payload: dict[str, np.ndarray],
    concurrency: int = 6,
    requests_per_worker: int = 1000,
    sort_scores: bool = True,
    warmup_requests: int = 3,
    payload_pool: list[dict[str, np.ndarray]] | None = None,
    prepared: bool = False,
    schedule: "np.ndarray | None" = None,
) -> BenchReport:
    """payload_pool, when given, varies the request bytes: worker w's i-th
    request sends pool[(w + i*STRIDE) % len(pool)] with STRIDE=73 (odd, so
    coprime to power-of-two pools): every worker cycles the FULL pool,
    concurrent workers hold distinct payloads, and batch compositions churn
    — the anti-flattering mode for content-addressed caches (the
    reference's own methodology re-sends ONE payload,
    DCNClient.java:208-210; both numbers are reported). A stride of
    `concurrency` would degenerate to period len(pool)/gcd and re-send a
    couple of payloads per worker.

    schedule, when given with payload_pool, REPLACES the stride walk with
    an explicit pool-index stream: worker w's i-th request sends
    pool[schedule[(w*requests_per_worker + i) % len(schedule)]] — the
    zipfian replay mode (zipfian_indices), where cache-on and cache-off
    runs must serve the byte-identical request sequence.

    prepared=True hoists the request build+serialize out of the loop
    (client.prepare + predict_prepared): the reference methodology already
    fixes the payload once (DCNClient.java:208-210), so the serialized
    bytes are loop-invariant too. Only meaningful without a payload_pool —
    the varied-payload mode exists to charge the FULL per-request path, so
    it always builds per call."""
    if prepared and payload_pool:
        raise ValueError("prepared mode is for the single-payload methodology; "
                         "payload_pool must charge the full build path")
    if schedule is not None and not payload_pool:
        raise ValueError("schedule indexes payload_pool; provide both")
    prep = client.prepare(payload) if prepared else None
    for _ in range(warmup_requests):
        if prep is not None:
            await client.predict_prepared(prep, sort_scores=sort_scores)
        else:
            await client.predict(payload, sort_scores=sort_scores)

    latencies: list[float] = []
    # Stride must be coprime to the pool size for EVERY worker to cycle the
    # FULL pool (73 alone would degenerate for pools of length 73k).
    stride = 1
    if payload_pool:
        stride = next(
            s for s in range(73, 73 + len(payload_pool) + 1)
            if math.gcd(s, len(payload_pool)) == 1
        )

    async def worker(w: int):
        for i in range(requests_per_worker):
            if prep is not None:
                t0 = time.perf_counter()
                scores = await client.predict_prepared(prep, sort_scores=sort_scores)
                latencies.append((time.perf_counter() - t0) * 1e3)
                assert scores.shape[0] == prep.candidates
                continue
            if schedule is not None:
                p = payload_pool[
                    schedule[(w * requests_per_worker + i) % len(schedule)]
                ]
            elif payload_pool:
                p = payload_pool[(w + i * stride) % len(payload_pool)]
            else:
                p = payload
            t0 = time.perf_counter()
            scores = await client.predict(p, sort_scores=sort_scores)
            latencies.append((time.perf_counter() - t0) * 1e3)
            assert scores.shape[0] == p["feat_ids"].shape[0]

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(concurrency)))
    wall = time.perf_counter() - t0
    return BenchReport(
        latencies_ms=np.asarray(latencies),
        wall_s=wall,
        concurrency=concurrency,
        requests_per_worker=requests_per_worker,
        candidates=payload["feat_ids"].shape[0],
    )


def _mp_load_worker(args) -> None:
    """Child-process load generator: its own event loop, channels, and GIL.

    Runs via the spawn context so it never inherits the parent's grpc/jax
    state; the client import chain is numpy+grpc only (no jax), keeping child
    startup cheap.
    """
    (hosts, model_name, channels_per_host, ids, wts, concurrency,
     requests_per_worker, sort_scores, warmup_requests, barrier, out_q) = args
    payload = {"feat_ids": ids, "feat_wts": wts}

    async def go():
        async with ShardedPredictClient(
            hosts, model_name, channels_per_host=channels_per_host
        ) as client:
            for _ in range(warmup_requests):
                await client.predict(payload, sort_scores=sort_scores)
            barrier.wait(timeout=120)  # all children warmed: start together
            return await run_closed_loop(
                client, payload,
                concurrency=concurrency,
                requests_per_worker=requests_per_worker,
                sort_scores=sort_scores,
                warmup_requests=0,
            )

    report = asyncio.run(go())
    # Report the child's own wall: perf_counter epochs are only comparable
    # within one process, so the parent aggregates per-child walls instead
    # of subtracting cross-process timestamps.
    out_q.put((report.latencies_ms, report.wall_s))


def run_closed_loop_mp(
    hosts: list[str],
    payload: dict[str, np.ndarray],
    model_name: str = "DCN",
    processes: int = 4,
    concurrency: int = 64,
    requests_per_worker: int = 15,
    sort_scores: bool = True,
    warmup_requests: int = 3,
    channels_per_host: int = 2,
) -> BenchReport:
    """Closed loop with the load generators in separate OS processes.

    The reference's 6 load threads ran on a JVM with real parallelism
    (DCNClient.java:213-224); a single CPython event loop serializes request
    marshalling behind the GIL it shares with the in-process server, so the
    generators move out of process. Wall time spans first-start to last-end
    across children (children synchronize on a barrier after warmup).
    """
    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    barrier = ctx.Barrier(processes)
    per_proc = max(1, concurrency // processes)
    args = [
        (hosts, model_name, channels_per_host, payload["feat_ids"], payload["feat_wts"],
         per_proc, requests_per_worker, sort_scores, warmup_requests, barrier, out_q)
        for _ in range(processes)
    ]
    procs = [ctx.Process(target=_mp_load_worker, args=(a,), daemon=True) for a in args]
    for p in procs:
        p.start()
    results = []
    try:
        while len(results) < len(procs):
            try:
                results.append(out_q.get(timeout=2))
            except queue.Empty:
                # Each child reports exactly once, right before exiting: more
                # finished children than reports (whatever the exitcode) means
                # someone died without reporting — fail fast, don't spin.
                finished = [p for p in procs if not p.is_alive()]
                if len(finished) > len(results):
                    # A report can still be in the feeder pipe between our
                    # get() timeout and the liveness scan; drain before
                    # declaring anyone dead.
                    try:
                        while True:
                            results.append(out_q.get_nowait())
                    except queue.Empty:
                        pass
                    if len(finished) > len(results):
                        raise RuntimeError(
                            f"{len(finished) - len(results)} load process(es) exited "
                            f"without reporting (exitcodes "
                            f"{[p.exitcode for p in finished]}); see their stderr "
                            "for the underlying error"
                        ) from None
    finally:
        for p in procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
    lat = np.concatenate([r[0] for r in results])
    # Children start together (post-warmup barrier), so the slowest child's
    # wall spans the whole run.
    wall = max(r[1] for r in results)
    return BenchReport(
        latencies_ms=lat,
        wall_s=wall,
        concurrency=per_proc * processes,
        requests_per_worker=requests_per_worker,
        candidates=payload["feat_ids"].shape[0],
    )
