"""Closed-loop benchmark harness — the reference's L6 layer, with percentiles.

Reproduces DCNClient.main's methodology (DCNClient.java:205-241): the payload
is built ONCE and re-sent for every request (DCNClient.java:208-210), N
concurrent workers each issue M sequential logical requests
(concurrentNum=6 x requestNum=1000 upstream), every request is wall-clock
timed end to end including the merge+sort, and an aggregate is reported.
The reference prints only the mean (DCNClient.java:234-236); BASELINE.md's
target metric set needs p50/p99 and QPS, so the raw sample list is kept and
summarized here.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time

import numpy as np

from .client import ShardedPredictClient


@dataclasses.dataclass
class BenchReport:
    latencies_ms: np.ndarray
    wall_s: float
    concurrency: int
    requests_per_worker: int
    candidates: int

    @property
    def requests(self) -> int:
        return self.latencies_ms.size

    def percentile(self, q: float) -> float:
        return float(np.percentile(self.latencies_ms, q))

    @property
    def qps(self) -> float:
        return self.requests / self.wall_s

    @property
    def candidates_per_s(self) -> float:
        return self.requests * self.candidates / self.wall_s

    def summary(self) -> dict:
        return {
            "requests": self.requests,
            "concurrency": self.concurrency,
            "candidates_per_request": self.candidates,
            "mean_ms": float(self.latencies_ms.mean()),
            "p50_ms": self.percentile(50),
            "p90_ms": self.percentile(90),
            "p99_ms": self.percentile(99),
            "qps": self.qps,
            "candidates_per_s": self.candidates_per_s,
            "wall_s": self.wall_s,
        }


def make_payload(candidates: int = 1500, num_fields: int = 43, seed: int = 7):
    """The reference workload point: [candidateNum, FIELD_NUM] int64 ids +
    float weights (DCNClient.java:25,29,57-74)."""
    rng = np.random.RandomState(seed)
    return {
        "feat_ids": rng.randint(0, 1 << 40, size=(candidates, num_fields)).astype(np.int64),
        "feat_wts": rng.rand(candidates, num_fields).astype(np.float32),
    }


async def run_closed_loop(
    client: ShardedPredictClient,
    payload: dict[str, np.ndarray],
    concurrency: int = 6,
    requests_per_worker: int = 1000,
    sort_scores: bool = True,
    warmup_requests: int = 3,
) -> BenchReport:
    for _ in range(warmup_requests):
        await client.predict(payload, sort_scores=sort_scores)

    latencies: list[float] = []

    async def worker():
        for _ in range(requests_per_worker):
            t0 = time.perf_counter()
            scores = await client.predict(payload, sort_scores=sort_scores)
            latencies.append((time.perf_counter() - t0) * 1e3)
            assert scores.shape[0] == payload["feat_ids"].shape[0]

    t0 = time.perf_counter()
    await asyncio.gather(*(worker() for _ in range(concurrency)))
    wall = time.perf_counter() - t0
    return BenchReport(
        latencies_ms=np.asarray(latencies),
        wall_s=wall,
        concurrency=concurrency,
        requests_per_worker=requests_per_worker,
        candidates=payload["feat_ids"].shape[0],
    )
