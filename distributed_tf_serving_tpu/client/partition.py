"""Candidate partitioning — the reference's client-side data parallelism.

`partition_list` reproduces the contiguous split of DCNClient.partitionList
(DCNClient.java:46-55): the first `parts-1` shards get floor(N/parts)
elements each and the last takes the remainder. The reference applies this
to *flattened* candidate x field arrays, which silently mis-aligns shard
boundaries whenever N*FIELD_NUM doesn't divide evenly (the latent bug at
DCNClient.java:97 — per-shard row count is recomputed as len/FIELD_NUM,
truncating). Here sharding happens on candidate *rows*, which is always
aligned; `partition_flat` exists for wire-parity testing and refuses the
misaligned case instead of truncating.
"""

from __future__ import annotations

from collections.abc import Sequence

import numpy as np


def partition_bounds(n: int, parts: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) bounds: floor(n/parts) each, remainder to last."""
    if parts <= 0:
        raise ValueError(f"parts must be positive, got {parts}")
    if n < parts:
        raise ValueError(f"cannot split {n} items into {parts} non-empty shards")
    base = n // parts
    bounds = [(i * base, (i + 1) * base) for i in range(parts - 1)]
    bounds.append(((parts - 1) * base, n))
    return bounds


def partition_list(seq: Sequence, parts: int) -> list[Sequence]:
    """Reference semantics (DCNClient.java:46-55) over any sequence."""
    return [seq[lo:hi] for lo, hi in partition_bounds(len(seq), parts)]


def shard_candidates(
    arrays: dict[str, np.ndarray], parts: int
) -> list[dict[str, np.ndarray]]:
    """Split candidate-major arrays into per-backend shards (row-aligned)."""
    n = next(iter(arrays.values())).shape[0]
    for key, arr in arrays.items():
        if arr.shape[0] != n:
            raise ValueError(
                f"inconsistent candidate counts: {key!r} has {arr.shape[0]}, expected {n}"
            )
    return [
        {k: v[lo:hi] for k, v in arrays.items()} for lo, hi in partition_bounds(n, parts)
    ]


def partition_flat(flat: Sequence, parts: int, num_fields: int) -> list[Sequence]:
    """The reference's flat-array split, with its misalignment made an error.

    The reference splits candidateNum*FIELD_NUM flat values and later infers
    each shard's row count as len/FIELD_NUM (DCNClient.java:57-74,97),
    silently dropping elements when shard boundaries fall mid-row. That case
    is rejected here.
    """
    shards = partition_list(flat, parts)
    for i, s in enumerate(shards):
        if len(s) % num_fields != 0:
            raise ValueError(
                f"shard {i} has {len(s)} elements, not a multiple of num_fields="
                f"{num_fields}: flat split would truncate mid-candidate "
                "(the DCNClient.java:97 misalignment)"
            )
    return shards


def jump_hash(key: int, buckets: int) -> int:
    """Lamping–Veach jump consistent hash: key -> [0, buckets). Cheap
    integer math per key (no per-bucket hashing), and consistent: growing
    the backend list from n to n+1 remaps only ~1/(n+1) of the keys, so a
    fleet resize does not cold-start every warm cache at once."""
    if buckets <= 0:
        raise ValueError(f"buckets must be positive, got {buckets}")
    key &= (1 << 64) - 1
    b, j = -1, 0
    while j < buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & ((1 << 64) - 1)
        j = int((b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def affinity_groups(
    arrays: dict[str, np.ndarray], parts: int
) -> list[tuple[int, np.ndarray, dict[str, np.ndarray]]]:
    """Key-affinity candidate placement (ROADMAP 4a seed): assign each
    candidate row to a backend by jump-hashing its canonical row digest
    (cache/digest.py row identity — the SAME bytes the server's dedup and
    label-join planes key on), then gather per-backend row groups.

    Returns [(home_backend_idx, original_row_indices, sub_arrays), ...]
    for the non-empty groups only. Every row appears in exactly one
    group; scattering each group's scores back by its indices
    reconstructs the original candidate order exactly (so results are
    bit-identical to the contiguous split — the same rows score the same
    on whichever replica, and order is restored by construction).

    The row digest is cache/digest.row_label_keys — the ONE per-row
    identity the server's dedup and label-join planes already key on
    (never a second implementation that could drift); its first 64 bits
    feed the jump hash. Cost is one blake2b per row on the predict path
    (~µs/row) — acceptable for the seed; a batched native digest is the
    follow-up if affinity graduates to the hot default.
    """
    from ..cache.digest import row_label_keys

    if parts <= 0:
        raise ValueError(f"parts must be positive, got {parts}")
    n = next(iter(arrays.values())).shape[0]
    if n == 0:
        raise ValueError("cannot place an empty candidate set")
    for key, arr in arrays.items():
        if arr.shape[0] != n:
            raise ValueError(
                f"inconsistent candidate counts: {key!r} has {arr.shape[0]}, expected {n}"
            )
    keys = row_label_keys(arrays)
    assign = np.empty(n, np.int64)
    for i in range(n):
        assign[i] = jump_hash(int(keys[i][:16], 16), parts)
    out = []
    for host in range(parts):
        idx = np.nonzero(assign == host)[0]
        if idx.size == 0:
            continue
        out.append((host, idx, {k: v[idx] for k, v in arrays.items()}))
    return out


def index_runs(indices: np.ndarray) -> tuple[tuple[int, int], ...]:
    """Sorted row indices -> contiguous [start, end) runs — the
    missing_ranges encoding for an affinity group's failure (its rows are
    scattered, so one group degrades into several small ranges)."""
    idx = np.sort(np.asarray(indices, np.int64))
    if idx.size == 0:
        return ()
    breaks = np.nonzero(np.diff(idx) > 1)[0]
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [idx.size - 1]))
    return tuple(
        (int(idx[s]), int(idx[e]) + 1) for s, e in zip(starts, ends)
    )


class StreamingMerger:
    """Incremental merge of out-of-order PredictStream chunks (ISSUE 9).

    The server flushes each sub-batch as its readback completes, so chunk
    arrival order is completion order, not offset order. The merger
    scatters each chunk into a preallocated result vector by its
    [offset, offset+count) range and tracks coverage, so the caller knows
    the instant the FIRST scores land (first-scores latency decoupled
    from the slowest sub-batch) and whether the stream fully covered the
    request before trusting the merge."""

    def __init__(self, total: int):
        if total <= 0:
            raise ValueError(f"total must be positive, got {total}")
        self.total = int(total)
        self.filled = 0
        self.chunks = 0
        self._out: np.ndarray | None = None
        self._covered = np.zeros(self.total, bool)

    def add(self, offset: int, values: np.ndarray) -> None:
        values = np.asarray(values)
        n = values.shape[0]
        if offset < 0 or offset + n > self.total:
            raise ValueError(
                f"chunk [{offset}, {offset + n}) outside request [0, {self.total})"
            )
        if self._out is None:
            # Geometry comes from the first chunk: dtype + per-candidate
            # trailing shape (scores are 1-D in practice, but the merge
            # works for any candidate-major output).
            self._out = np.empty((self.total,) + values.shape[1:], values.dtype)
        seg = self._covered[offset: offset + n]
        if seg.any():
            raise ValueError(
                f"chunk [{offset}, {offset + n}) overlaps rows already merged"
            )
        seg[:] = True
        self._out[offset: offset + n] = values
        self.filled += n
        self.chunks += 1

    @property
    def complete(self) -> bool:
        return self.filled == self.total

    def missing_ranges(self) -> tuple[tuple[int, int], ...]:
        """Contiguous [start, end) ranges the stream never covered."""
        out, start = [], None
        for i, covered in enumerate(self._covered):
            if not covered and start is None:
                start = i
            elif covered and start is not None:
                out.append((start, i))
                start = None
        if start is not None:
            out.append((start, self.total))
        return tuple(out)

    def result(self) -> np.ndarray:
        if not self.complete:
            raise ValueError(
                f"stream covered {self.filled}/{self.total} candidates; "
                f"missing {self.missing_ranges()}"
            )
        assert self._out is not None
        return self._out


def merge_host_order(parts: list[np.ndarray]) -> np.ndarray:
    """Concatenate per-shard results in shard (host) order — the merge
    semantics of DCNClient.java:161-164. A single WRITABLE shard passes
    through uncopied; read-only shards (codec's zero-copy frombuffer views
    over response bytes) are copied so callers always get the owned,
    writable array this function has always returned."""
    if len(parts) == 1:
        p = np.asarray(parts[0])
        return p if p.flags.writeable else p.copy()
    return np.concatenate(list(parts), axis=0)
