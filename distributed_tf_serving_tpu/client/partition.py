"""Candidate partitioning — the reference's client-side data parallelism.

`partition_list` reproduces the contiguous split of DCNClient.partitionList
(DCNClient.java:46-55): the first `parts-1` shards get floor(N/parts)
elements each and the last takes the remainder. The reference applies this
to *flattened* candidate x field arrays, which silently mis-aligns shard
boundaries whenever N*FIELD_NUM doesn't divide evenly (the latent bug at
DCNClient.java:97 — per-shard row count is recomputed as len/FIELD_NUM,
truncating). Here sharding happens on candidate *rows*, which is always
aligned; `partition_flat` exists for wire-parity testing and refuses the
misaligned case instead of truncating.
"""

from __future__ import annotations

from collections.abc import Sequence

import numpy as np


def partition_bounds(n: int, parts: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) bounds: floor(n/parts) each, remainder to last."""
    if parts <= 0:
        raise ValueError(f"parts must be positive, got {parts}")
    if n < parts:
        raise ValueError(f"cannot split {n} items into {parts} non-empty shards")
    base = n // parts
    bounds = [(i * base, (i + 1) * base) for i in range(parts - 1)]
    bounds.append(((parts - 1) * base, n))
    return bounds


def partition_list(seq: Sequence, parts: int) -> list[Sequence]:
    """Reference semantics (DCNClient.java:46-55) over any sequence."""
    return [seq[lo:hi] for lo, hi in partition_bounds(len(seq), parts)]


def shard_candidates(
    arrays: dict[str, np.ndarray], parts: int
) -> list[dict[str, np.ndarray]]:
    """Split candidate-major arrays into per-backend shards (row-aligned)."""
    n = next(iter(arrays.values())).shape[0]
    for key, arr in arrays.items():
        if arr.shape[0] != n:
            raise ValueError(
                f"inconsistent candidate counts: {key!r} has {arr.shape[0]}, expected {n}"
            )
    return [
        {k: v[lo:hi] for k, v in arrays.items()} for lo, hi in partition_bounds(n, parts)
    ]


def partition_flat(flat: Sequence, parts: int, num_fields: int) -> list[Sequence]:
    """The reference's flat-array split, with its misalignment made an error.

    The reference splits candidateNum*FIELD_NUM flat values and later infers
    each shard's row count as len/FIELD_NUM (DCNClient.java:57-74,97),
    silently dropping elements when shard boundaries fall mid-row. That case
    is rejected here.
    """
    shards = partition_list(flat, parts)
    for i, s in enumerate(shards):
        if len(s) % num_fields != 0:
            raise ValueError(
                f"shard {i} has {len(s)} elements, not a multiple of num_fields="
                f"{num_fields}: flat split would truncate mid-candidate "
                "(the DCNClient.java:97 misalignment)"
            )
    return shards


def merge_host_order(parts: list[np.ndarray]) -> np.ndarray:
    """Concatenate per-shard results in shard (host) order — the merge
    semantics of DCNClient.java:161-164. A single WRITABLE shard passes
    through uncopied; read-only shards (codec's zero-copy frombuffer views
    over response bytes) are copied so callers always get the owned,
    writable array this function has always returned."""
    if len(parts) == 1:
        p = np.asarray(parts[0])
        return p if p.flags.writeable else p.copy()
    return np.concatenate(list(parts), axis=0)
