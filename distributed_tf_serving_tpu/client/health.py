"""Per-backend health scoreboard — health-aware shard placement + failover.

The reference printed a failed shard and dropped it (DCNClient.java:158-159);
PR 1's failover rotated blindly to the next host — a wedged backend still
costs a full timeout per shard attempt, every request, until someone
restarts it. Production fan-out serving ("Scaling TensorFlow to 300 million
predictions per second") routes AROUND sick backends instead:

- **EWMA latency** per backend (observability + the hedge-target ranking);
- **consecutive-failure ejection**: after `failure_threshold` consecutive
  reroutable failures the backend is ejected for `ejection_s` (doubling per
  repeat up to `max_ejection_s`);
- **half-open probing**: once the ejection interval passes, exactly ONE
  in-flight request (or an explicit grpc.health.v1 Check, see
  client.ShardedPredictClient.health_probe) is allowed through; success
  recovers the backend, failure re-ejects it with a doubled interval;
- **pushback is "busy", not "dead"** (overload plane, serving/overload.py):
  a RESOURCE_EXHAUSTED shed is recorded with kind="pushback" — it proves
  the backend ALIVE (it answered), so it never consumes the consecutive-
  failure ejection budget. Instead the host is marked busy for the
  server's retry-after hint (or a configured default): steering prefers
  non-busy healthy hosts and hedges never target a busy one. Without this
  distinction a healthy-but-shedding backend gets ejected and its traffic
  piles onto the remaining hosts, overloading them next — the ejection
  cascade that turns one hot host into a fleet-wide brownout.

The scoreboard only STEERS (pick()); the client still owns retry/hedge
mechanics. Pure in-process bookkeeping: one lock, an injectable clock so
the ejection/half-open timeline is deterministic under test.
"""

from __future__ import annotations

import dataclasses
import threading
import time

HEALTHY, EJECTED, HALF_OPEN = "healthy", "ejected", "half_open"
# Draining (ISSUE 17 satellite): the host ANNOUNCED it is leaving
# (GracefulShutdown refusal detail / NOT_SERVING-with-reason health
# answer). Distinct from EJECTED (no ejection budget was spent, no
# doubling) and from the rebuilding busy-bias (a drain is not coming
# back within an MTTR): steering skips the host entirely until
# draining_probe_s passes, then half-open probing lets a RESTARTED
# process on the same address rejoin.
DRAINING = "draining"


@dataclasses.dataclass(frozen=True)
class ScoreboardConfig:
    # Consecutive reroutable failures before ejection. 1 would eject on any
    # single blip; 3 tolerates isolated packet-loss-shaped noise while still
    # reacting within one request burst to a genuinely down backend.
    failure_threshold: int = 3
    # First ejection interval; doubles on each half-open probe failure.
    ejection_s: float = 5.0
    max_ejection_s: float = 60.0
    # EWMA smoothing for per-backend latency (0 < alpha <= 1).
    ewma_alpha: float = 0.2
    # How long a pushback (kind="pushback" failure — an overload shed)
    # biases steering away from the busy host when the server sent no
    # retry-after hint. Short on purpose: overload drains in queue-wait
    # units, not ejection units.
    pushback_busy_s: float = 0.25
    # How long a rebuilding hint (kind="rebuilding" — a quarantined
    # replica's UNAVAILABLE refusal or a NOT_SERVING health answer
    # during its recovery cycle) biases steering away. Sized to the
    # measured recovery MTTR (~1-4s): long enough to skip the rebuild,
    # short enough that the recovered replica gets traffic back without
    # waiting out an ejection window it never earned.
    rebuilding_busy_s: float = 2.0
    # CONSECUTIVE rebuilding hints (no intervening success) a host may
    # accumulate before further ones count as ordinary failures again.
    # A genuine recovery cycle resolves within its MTTR — one or two
    # hints; a DRAINING replica (health also answers NOT_SERVING while
    # leaving) or a replica stuck in endless quarantine would otherwise
    # cycle healthy-busy forever with the ejection backoff zeroed each
    # round. Past the streak, the normal eject-with-doubling machinery
    # takes over.
    rebuilding_streak_limit: int = 3
    # How long a DRAINING host (kind="draining" — the backend announced
    # a graceful shutdown) is held out of steering before half-open
    # probing checks whether a restarted process took over the address.
    # Unlike the rebuilding window this is not an MTTR estimate — a
    # draining replica is leaving — it is the probe cadence for the
    # replacement process. Never consumes the ejection budget and never
    # cycles the rebuilding_busy_s retry window.
    draining_probe_s: float = 3.0
    # How long a corrupt-response verdict (kind="corrupt" — the
    # integrity plane's CRC verify caught a response whose score bytes
    # mismatch their stamped checksum, ISSUE 20) biases steering away.
    # Sized to the server's own shadow-verification / recovery reaction
    # window: long enough for the replica's self-check to run, short
    # enough that one cosmic-ray flip does not exile a healthy host.
    corrupt_busy_s: float = 2.0
    # CONSECUTIVE corrupt verdicts (no intervening clean success) before
    # further ones count as ordinary failures: a single flipped bit is
    # noise, a host that keeps serving mismatched bytes has a sick data
    # path and must walk the eject-with-doubling machinery — but never
    # on the first hit (the ISSUE 20 contract).
    corrupt_streak_limit: int = 3


@dataclasses.dataclass
class _HostState:
    state: str = HEALTHY
    consecutive_failures: int = 0
    ejected_until: float = 0.0
    current_ejection_s: float = 0.0
    probe_inflight: bool = False
    ewma_ms: float | None = None
    successes: int = 0
    failures: int = 0
    # Overload pushback: the host is alive but shedding. Steering prefers
    # other healthy hosts until busy_until passes; the ejection machinery
    # never sees these.
    pushbacks: int = 0
    busy_until: float = 0.0
    # Recovery-plane rebuilds announced by the host itself (ISSUE 12
    # satellite): alive, answering, temporarily refusing — shares the
    # busy_until steering bias, never the ejection budget. The
    # consecutive streak (reset by any success) bounds how long the
    # hint can defer ejection — see rebuilding_streak_limit.
    rebuilds: int = 0
    consecutive_rebuilds: int = 0
    # Drain hints (ISSUE 17 satellite): the host said it is shutting
    # down. State flips to DRAINING — skipped by steering outright —
    # with no ejection budget spent and no rebuilding streak cycled.
    drains: int = 0
    # Corrupt-response verdicts (ISSUE 20): the host ANSWERED but its
    # score bytes failed the integrity CRC verify. Busy-biased steering
    # like pushback; the consecutive streak (reset by any clean
    # success) bounds how long before ordinary ejection takes over.
    corruptions: int = 0
    consecutive_corruptions: int = 0


class BackendScoreboard:
    """Thread-safe (asyncio callbacks + any direct callers) per-backend
    scoreboard over a FIXED host list, indexed like the client's."""

    def __init__(
        self,
        hosts: list[str],
        config: ScoreboardConfig | None = None,
        clock=time.monotonic,
    ):
        if not hosts:
            raise ValueError("need at least one backend host")
        self.hosts = list(hosts)
        self.config = config or ScoreboardConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._states = [_HostState() for _ in self.hosts]
        # Event counters (bench.py / soak report them; names are the
        # acceptance-criteria vocabulary).
        self.ejections = 0
        self.probes = 0
        self.recoveries = 0
        self.pushbacks = 0
        # Rebuilding hints (ISSUE 12 satellite): quarantine refusals /
        # NOT_SERVING health answers recorded as kind="rebuilding".
        self.rebuilds = 0
        # Drain hints (ISSUE 17 satellite): "server is draining" refusals
        # / NOT_SERVING-while-draining health answers recorded as
        # kind="draining" — steered away from immediately, no ejection
        # budget spent, no rebuilding retry window cycled.
        self.drains = 0
        # Retry-budget trips (ISSUE 11): requests whose per-request
        # attempt cap (client max_attempts_total) ran dry — the
        # storm-suppression evidence next to the ejection counters it
        # guards against amplifying.
        self.retry_budget_exhausted = 0
        # Corrupt-response verdicts (ISSUE 20): integrity CRC verify
        # failures recorded as kind="corrupt" — busy-biased steering,
        # never ejection on the first hit.
        self.corruptions = 0

    # ------------------------------------------------------------ recording

    def record_success(self, idx: int, latency_s: float | None = None) -> None:
        with self._lock:
            st = self._states[idx]
            st.successes += 1
            st.consecutive_failures = 0
            st.consecutive_rebuilds = 0
            st.consecutive_corruptions = 0
            if latency_s is not None:
                ms = latency_s * 1e3
                a = self.config.ewma_alpha
                st.ewma_ms = ms if st.ewma_ms is None else (1 - a) * st.ewma_ms + a * ms
            if st.state != HEALTHY:
                # Half-open probe succeeded (or a raced request landed while
                # ejected): the backend is back.
                st.state = HEALTHY
                st.probe_inflight = False
                st.current_ejection_s = 0.0
                self.recoveries += 1

    def record_failure(
        self, idx: int, kind: str = "failure",
        retry_after_s: float | None = None,
    ) -> None:
        """One failed attempt on backend `idx`.

        kind="failure" (default): a reroutable failure — the backend may be
        dead; counts toward the consecutive-failure ejection budget.
        kind="rebuilding": the backend itself announced a recovery-cycle
        rebuild (a quarantine UNAVAILABLE refusal, or NOT_SERVING from
        its health service mid-cycle) — it is provably alive and will be
        back within its MTTR, so it is marked busy for rebuilding_busy_s
        (or the caller-provided window) and steered around WITHOUT
        touching the ejection budget; exactly the PR-5
        pushback-is-not-death pattern applied below the RPC layer.
        kind="pushback": an overload shed (RESOURCE_EXHAUSTED with the
        serving stack's retry-after hint) — the backend ANSWERED, so it is
        provably alive; it is marked busy for `retry_after_s` (or the
        configured pushback_busy_s) and steered around, but the ejection
        budget is untouched. A pushback landing on a half-open/ejected
        host is the probe succeeding at being alive: the host recovers to
        HEALTHY (busy) instead of re-ejecting with a doubled interval —
        without this, a fleet-wide overload turns into a fleet-wide
        ejection cascade and the survivors inherit ALL the traffic.
        kind="draining": the backend announced a graceful shutdown (the
        drain refusal detail, a NOT_SERVING health answer carrying the
        draining reason, or a fleet gossip record) — it is leaving, not
        recovering, so it flips to the DRAINING state: steering skips it
        outright from the FIRST hint (zero further routed requests while
        an alternative exists), the ejection budget is untouched, and
        the rebuilding busy window is never cycled. After
        draining_probe_s, half-open probing checks whether a restarted
        process took over the address.
        kind="corrupt": the backend ANSWERED but its response failed the
        integrity plane's CRC verify (ISSUE 20) — alive with a suspect
        data path. Busy-biased steering for corrupt_busy_s (the
        pushback pattern: NEVER ejection on the first hit — one flipped
        bit must not exile a healthy host), while the consecutive
        streak (reset by any clean success) hands a host that KEEPS
        serving mismatched bytes to the ordinary eject-with-doubling
        machinery past corrupt_streak_limit."""
        with self._lock:
            st = self._states[idx]
            if kind == "draining":
                st.drains += 1
                self.drains += 1
                st.consecutive_failures = 0
                st.consecutive_rebuilds = 0
                st.state = DRAINING
                st.probe_inflight = False
                st.current_ejection_s = 0.0
                # Reuse the ejected_until timeline for the probe-again
                # horizon; repeated hints extend it (the replica is still
                # announcing its exit).
                st.ejected_until = self._clock() + self.config.draining_probe_s
                return
            if kind == "rebuilding" and \
                    st.consecutive_rebuilds >= self.config.rebuilding_streak_limit:
                # The host has announced "rebuilding" this many times in a
                # row without once answering a request: that is a draining
                # replica (its health also reads NOT_SERVING) or a
                # quarantine loop, not a bounded recovery cycle. Fall
                # through to the ordinary failure path so the
                # eject-with-doubling machinery bounds further probing.
                kind = "failure"
            if kind == "rebuilding":
                st.rebuilds += 1
                st.consecutive_rebuilds += 1
                self.rebuilds += 1
                busy = (
                    retry_after_s if retry_after_s is not None
                    else self.config.rebuilding_busy_s
                )
                st.busy_until = max(st.busy_until, self._clock() + busy)
                # The refusal PROVES the host answers (same reasoning as
                # the pushback branch): the failure streak is over, and
                # an ejected/half-open host that announced its rebuild
                # recovers to HEALTHY (busy) instead of re-ejecting with
                # a doubled interval.
                st.consecutive_failures = 0
                if st.state != HEALTHY:
                    st.state = HEALTHY
                    st.probe_inflight = False
                    st.current_ejection_s = 0.0
                    self.recoveries += 1
                return
            if kind == "corrupt":
                if st.consecutive_corruptions >= \
                        self.config.corrupt_streak_limit:
                    # The host keeps serving bytes that fail the CRC
                    # verify with no clean answer in between: a sick
                    # data path, not a cosmic ray. Fall through to the
                    # ordinary failure path so eject-with-doubling
                    # bounds further exposure.
                    kind = "failure"
                else:
                    st.corruptions += 1
                    st.consecutive_corruptions += 1
                    self.corruptions += 1
                    busy = (
                        retry_after_s if retry_after_s is not None
                        else self.config.corrupt_busy_s
                    )
                    st.busy_until = max(st.busy_until, self._clock() + busy)
                    # The mismatched answer still PROVES the host
                    # answers: the failure streak is over, and an
                    # ejected/half-open host recovers to HEALTHY (busy)
                    # — the integrity verdict steers, the ejection
                    # machinery only takes over past the streak limit.
                    st.consecutive_failures = 0
                    if st.state != HEALTHY:
                        st.state = HEALTHY
                        st.probe_inflight = False
                        st.current_ejection_s = 0.0
                        self.recoveries += 1
                    return
            if kind == "pushback":
                st.pushbacks += 1
                self.pushbacks += 1
                busy = (
                    retry_after_s
                    if retry_after_s is not None
                    else self.config.pushback_busy_s
                )
                st.busy_until = max(st.busy_until, self._clock() + busy)
                # A pushback PROVES the host answers, exactly like a
                # success does: the consecutive-failure streak is over.
                # Leaving it at/above the threshold would let ONE later
                # transient failure instantly re-eject a host that just
                # demonstrated it is alive — a hair-trigger version of the
                # very cascade this kind= split exists to prevent.
                st.consecutive_failures = 0
                if st.state != HEALTHY:
                    # Alive-but-busy beats ejected: recover, keep the bias.
                    st.state = HEALTHY
                    st.probe_inflight = False
                    st.current_ejection_s = 0.0
                    self.recoveries += 1
                return
            st.failures += 1
            st.consecutive_failures += 1
            if st.state == HALF_OPEN:
                # Probe failed: re-eject with a doubled interval.
                self._eject_locked(st, double=True)
            elif (
                st.state == HEALTHY
                and st.consecutive_failures >= self.config.failure_threshold
            ):
                self._eject_locked(st, double=False)
            elif st.state == EJECTED:
                st.probe_inflight = False  # raced request while ejected

    def _eject_locked(self, st: _HostState, double: bool) -> None:
        interval = (
            min(st.current_ejection_s * 2, self.config.max_ejection_s)
            if double and st.current_ejection_s
            else self.config.ejection_s
        )
        st.state = EJECTED
        st.current_ejection_s = interval
        st.ejected_until = self._clock() + interval
        st.probe_inflight = False
        self.ejections += 1

    # ------------------------------------------------------------- steering

    def _advance_locked(self, st: _HostState) -> None:
        if (
            st.state in (EJECTED, DRAINING)
            and self._clock() >= st.ejected_until
        ):
            st.state = HALF_OPEN
            st.probe_inflight = False

    def pick(self, preferred: int, exclude: tuple[int, ...] = ()) -> int | None:
        """Backend index for a shard homed at `preferred`: the home host
        when healthy — or HALF_OPEN with a free probe slot (the caller's
        request IS the probe; without home-priority a half-open host would
        be starved of probes forever while its healthy peers absorb the
        rotation, and never recover) — else the first HEALTHY host rotating
        from `preferred`, else any half-open host with a free slot, else —
        everything ejected — the rotation's first non-excluded host
        (sending somewhere beats failing without trying). None only when
        every host is excluded (failover exhausted the list).

        Pushback bias: among HEALTHY hosts, one the overload plane marked
        busy (a recent RESOURCE_EXHAUSTED shed) is passed over while a
        non-busy healthy peer exists — but when EVERY healthy host is
        busy the rotation applies unchanged (spreading load across busy
        hosts beats refusing to send)."""
        n = len(self.hosts)
        order = [(preferred + k) % n for k in range(n) if (preferred + k) % n not in exclude]
        if not order:
            return None
        with self._lock:
            now = self._clock()
            for i in order:
                self._advance_locked(self._states[i])
            home = self._states[order[0]]
            if (
                order[0] == preferred % n
                and home.state == HALF_OPEN
                and not home.probe_inflight
            ):
                home.probe_inflight = True
                self.probes += 1
                return order[0]
            for i in order:
                st = self._states[i]
                if st.state == HEALTHY and st.busy_until <= now:
                    return i
            for i in order:
                if self._states[i].state == HEALTHY:
                    return i  # every healthy host busy: rotation order
            for i in order:
                st = self._states[i]
                if st.state == HALF_OPEN and not st.probe_inflight:
                    st.probe_inflight = True
                    self.probes += 1
                    return i
            return order[0]

    def state(self, idx: int) -> str:
        with self._lock:
            self._advance_locked(self._states[idx])
            return self._states[idx].state

    def note_retry_budget_exhausted(self) -> None:
        """One request's attempt budget ran out (client retry-budget
        satellite): counted here so the scoreboard snapshot — the
        resilience surface benches/soaks already read — carries it."""
        with self._lock:
            self.retry_budget_exhausted += 1

    def release_probe(self, idx: int) -> None:
        """Free a half-open probe slot whose request was CANCELLED (hedge
        loser) — neither success nor failure was observed, so the slot must
        not stay taken forever and starve future probes."""
        with self._lock:
            self._states[idx].probe_inflight = False

    def hedge_target(self, exclude: tuple[int, ...]) -> int | None:
        """Best extra host for a hedged attempt: healthy, lowest EWMA,
        not already in use. None = nowhere sensible to hedge. A host the
        overload plane marked busy is never hedged into — a hedge is
        OPTIONAL duplicate work, exactly what a shedding backend asked
        not to receive."""
        with self._lock:
            now = self._clock()
            best, best_ms = None, None
            for i, st in enumerate(self._states):
                if i in exclude:
                    continue
                self._advance_locked(st)
                if st.state != HEALTHY or st.busy_until > now:
                    continue
                ms = st.ewma_ms if st.ewma_ms is not None else float("inf")
                if best is None or ms < best_ms:
                    best, best_ms = i, ms
            return best

    # ---------------------------------------------------------- observation

    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            return {
                "ejections": self.ejections,
                "probes": self.probes,
                "recoveries": self.recoveries,
                "pushbacks": self.pushbacks,
                "rebuilds": self.rebuilds,
                "drains": self.drains,
                "corruptions": self.corruptions,
                "retry_budget_exhausted": self.retry_budget_exhausted,
                "backends": {
                    host: {
                        "state": st.state,
                        "ewma_ms": round(st.ewma_ms, 3) if st.ewma_ms is not None else None,
                        "consecutive_failures": st.consecutive_failures,
                        "successes": st.successes,
                        "failures": st.failures,
                        "pushbacks": st.pushbacks,
                        "rebuilds": st.rebuilds,
                        "drains": st.drains,
                        "corruptions": st.corruptions,
                        "busy": st.busy_until > now,
                    }
                    for host, st in zip(self.hosts, self._states)
                },
            }
