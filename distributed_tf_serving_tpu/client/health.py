"""Per-backend health scoreboard — health-aware shard placement + failover.

The reference printed a failed shard and dropped it (DCNClient.java:158-159);
PR 1's failover rotated blindly to the next host — a wedged backend still
costs a full timeout per shard attempt, every request, until someone
restarts it. Production fan-out serving ("Scaling TensorFlow to 300 million
predictions per second") routes AROUND sick backends instead:

- **EWMA latency** per backend (observability + the hedge-target ranking);
- **consecutive-failure ejection**: after `failure_threshold` consecutive
  reroutable failures the backend is ejected for `ejection_s` (doubling per
  repeat up to `max_ejection_s`);
- **half-open probing**: once the ejection interval passes, exactly ONE
  in-flight request (or an explicit grpc.health.v1 Check, see
  client.ShardedPredictClient.health_probe) is allowed through; success
  recovers the backend, failure re-ejects it with a doubled interval.

The scoreboard only STEERS (pick()); the client still owns retry/hedge
mechanics. Pure in-process bookkeeping: one lock, an injectable clock so
the ejection/half-open timeline is deterministic under test.
"""

from __future__ import annotations

import dataclasses
import threading
import time

HEALTHY, EJECTED, HALF_OPEN = "healthy", "ejected", "half_open"


@dataclasses.dataclass(frozen=True)
class ScoreboardConfig:
    # Consecutive reroutable failures before ejection. 1 would eject on any
    # single blip; 3 tolerates isolated packet-loss-shaped noise while still
    # reacting within one request burst to a genuinely down backend.
    failure_threshold: int = 3
    # First ejection interval; doubles on each half-open probe failure.
    ejection_s: float = 5.0
    max_ejection_s: float = 60.0
    # EWMA smoothing for per-backend latency (0 < alpha <= 1).
    ewma_alpha: float = 0.2


@dataclasses.dataclass
class _HostState:
    state: str = HEALTHY
    consecutive_failures: int = 0
    ejected_until: float = 0.0
    current_ejection_s: float = 0.0
    probe_inflight: bool = False
    ewma_ms: float | None = None
    successes: int = 0
    failures: int = 0


class BackendScoreboard:
    """Thread-safe (asyncio callbacks + any direct callers) per-backend
    scoreboard over a FIXED host list, indexed like the client's."""

    def __init__(
        self,
        hosts: list[str],
        config: ScoreboardConfig | None = None,
        clock=time.monotonic,
    ):
        if not hosts:
            raise ValueError("need at least one backend host")
        self.hosts = list(hosts)
        self.config = config or ScoreboardConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._states = [_HostState() for _ in self.hosts]
        # Event counters (bench.py / soak report them; names are the
        # acceptance-criteria vocabulary).
        self.ejections = 0
        self.probes = 0
        self.recoveries = 0

    # ------------------------------------------------------------ recording

    def record_success(self, idx: int, latency_s: float | None = None) -> None:
        with self._lock:
            st = self._states[idx]
            st.successes += 1
            st.consecutive_failures = 0
            if latency_s is not None:
                ms = latency_s * 1e3
                a = self.config.ewma_alpha
                st.ewma_ms = ms if st.ewma_ms is None else (1 - a) * st.ewma_ms + a * ms
            if st.state != HEALTHY:
                # Half-open probe succeeded (or a raced request landed while
                # ejected): the backend is back.
                st.state = HEALTHY
                st.probe_inflight = False
                st.current_ejection_s = 0.0
                self.recoveries += 1

    def record_failure(self, idx: int) -> None:
        with self._lock:
            st = self._states[idx]
            st.failures += 1
            st.consecutive_failures += 1
            if st.state == HALF_OPEN:
                # Probe failed: re-eject with a doubled interval.
                self._eject_locked(st, double=True)
            elif (
                st.state == HEALTHY
                and st.consecutive_failures >= self.config.failure_threshold
            ):
                self._eject_locked(st, double=False)
            elif st.state == EJECTED:
                st.probe_inflight = False  # raced request while ejected

    def _eject_locked(self, st: _HostState, double: bool) -> None:
        interval = (
            min(st.current_ejection_s * 2, self.config.max_ejection_s)
            if double and st.current_ejection_s
            else self.config.ejection_s
        )
        st.state = EJECTED
        st.current_ejection_s = interval
        st.ejected_until = self._clock() + interval
        st.probe_inflight = False
        self.ejections += 1

    # ------------------------------------------------------------- steering

    def _advance_locked(self, st: _HostState) -> None:
        if st.state == EJECTED and self._clock() >= st.ejected_until:
            st.state = HALF_OPEN
            st.probe_inflight = False

    def pick(self, preferred: int, exclude: tuple[int, ...] = ()) -> int | None:
        """Backend index for a shard homed at `preferred`: the home host
        when healthy — or HALF_OPEN with a free probe slot (the caller's
        request IS the probe; without home-priority a half-open host would
        be starved of probes forever while its healthy peers absorb the
        rotation, and never recover) — else the first HEALTHY host rotating
        from `preferred`, else any half-open host with a free slot, else —
        everything ejected — the rotation's first non-excluded host
        (sending somewhere beats failing without trying). None only when
        every host is excluded (failover exhausted the list)."""
        n = len(self.hosts)
        order = [(preferred + k) % n for k in range(n) if (preferred + k) % n not in exclude]
        if not order:
            return None
        with self._lock:
            for i in order:
                self._advance_locked(self._states[i])
            home = self._states[order[0]]
            if (
                order[0] == preferred % n
                and home.state == HALF_OPEN
                and not home.probe_inflight
            ):
                home.probe_inflight = True
                self.probes += 1
                return order[0]
            for i in order:
                if self._states[i].state == HEALTHY:
                    return i
            for i in order:
                st = self._states[i]
                if st.state == HALF_OPEN and not st.probe_inflight:
                    st.probe_inflight = True
                    self.probes += 1
                    return i
            return order[0]

    def state(self, idx: int) -> str:
        with self._lock:
            self._advance_locked(self._states[idx])
            return self._states[idx].state

    def release_probe(self, idx: int) -> None:
        """Free a half-open probe slot whose request was CANCELLED (hedge
        loser) — neither success nor failure was observed, so the slot must
        not stay taken forever and starve future probes."""
        with self._lock:
            self._states[idx].probe_inflight = False

    def hedge_target(self, exclude: tuple[int, ...]) -> int | None:
        """Best extra host for a hedged attempt: healthy, lowest EWMA,
        not already in use. None = nowhere sensible to hedge."""
        with self._lock:
            best, best_ms = None, None
            for i, st in enumerate(self._states):
                if i in exclude:
                    continue
                self._advance_locked(st)
                if st.state != HEALTHY:
                    continue
                ms = st.ewma_ms if st.ewma_ms is not None else float("inf")
                if best is None or ms < best_ms:
                    best, best_ms = i, ms
            return best

    # ---------------------------------------------------------- observation

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ejections": self.ejections,
                "probes": self.probes,
                "recoveries": self.recoveries,
                "backends": {
                    host: {
                        "state": st.state,
                        "ewma_ms": round(st.ewma_ms, 3) if st.ewma_ms is not None else None,
                        "consecutive_failures": st.consecutive_failures,
                        "successes": st.successes,
                        "failures": st.failures,
                    }
                    for host, st in zip(self.hosts, self._states)
                },
            }
