"""Fan-out Predict client — the reference's split/merge path, asyncio-native.

Reproduces C2-C6/C9 of the component inventory (SURVEY.md §2.1): one
long-lived channel per backend host shared by all in-flight requests
(DCNClient.java:118-125), per-request candidate sharding (contiguous,
remainder-to-last), concurrent per-shard Predict RPCs, host-order merge of
each shard's output tensor (DCNClient.java:161-164), and optional ascending
sort of the merged scores — the ranking step (DCNClient.java:195).

Improvements over the reference kept deliberately semantic-preserving:
asyncio tasks replace the 16-thread pool + blocking stubs (asynchrony moves
into gRPC itself), per-RPC deadlines + typed errors replace
print-and-drop/thread-death failure modes (DCNClient.java:158-159,185-188),
and channels actually close (the reference's shutDownChannels never calls
shutdown(), DCNClient.java:127-135).
"""

from __future__ import annotations

import asyncio
import dataclasses

import grpc
import grpc.aio
import numpy as np

from .. import codec
from ..proto import serving_apis_pb2 as apis
# LARGE_MESSAGE_CHANNEL_OPTIONS re-exported: transport tuning lives with
# the grpc wiring, but callers historically reach it through the client.
from ..proto.service_grpc import (  # noqa: F401
    LARGE_MESSAGE_CHANNEL_OPTIONS,
    PredictionServiceStub,
)
from .partition import merge_host_order, shard_candidates


class PredictClientError(RuntimeError):
    def __init__(self, host: str, code, details: str):
        super().__init__(f"Predict to {host} failed: {code} {details}")
        self.host = host
        self.code = code


@dataclasses.dataclass
class PreparedRequest:
    """A logical request pre-sharded and pre-serialized to wire bytes.

    For hot candidate sets that are re-scored continuously (the reference's
    own benchmark re-sends ONE payload for all 6,000 requests,
    DCNClient.java:208-210), building + serializing the half-MB
    PredictRequest per call is pure re-work — on a single-core client it is
    ~10% of the whole request budget (round-3 profile: 220 us of 2.4 ms).
    prepare() hoists it out of the loop; predict_prepared() sends the cached
    bytes through the raw-bytes stub. The wire bytes are identical to
    predict()'s."""

    shard_blobs: list[bytes]
    candidates: int


# Failures worth rerouting to another backend: the host is down/slow/
# shedding. Deterministic request errors (INVALID_ARGUMENT, NOT_FOUND)
# would fail identically everywhere and never retry.
_FAILOVER_CODES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED"})


def compact_payload(
    arrays: dict[str, np.ndarray], vocab_size: int
) -> dict[str, np.ndarray]:
    """Pre-apply the server's own first transforms client-side so the wire
    carries half the bytes: int64 ids -> folded int32 (exact mod, the
    server's host fold; models re-fold idempotently) and f32 weights ->
    bf16 (the models' compute-dtype cast, round-to-nearest-even both
    sides). Scores are bit-identical to the wide encoding — the packed
    device bytes are the same — while the 516 KB reference request becomes
    258 KB. The transport is >half the single-core request budget (~1.7
    ms/MB through grpc-python), so this is the client knob with the largest
    throughput effect; the server accepts it via the compact-wire widening
    in service._decode_and_validate."""
    import ml_dtypes

    from .. import native

    out = {}
    for k, v in arrays.items():
        if k == "feat_ids" and v.dtype == np.int64:
            # The server's own canonical fold (native one-pass when built).
            out[k] = native.fold_ids(v, vocab_size)
        elif k == "feat_wts" and v.dtype == np.float32:
            # ONLY the weights input: other float inputs (DLRM
            # dense_features) are consumed in f32 by the models and the
            # server rejects them in bf16 (service widening gate).
            out[k] = v.astype(ml_dtypes.bfloat16)
        else:
            out[k] = v
    return out


def build_predict_request(
    arrays: dict[str, np.ndarray],
    model_name: str,
    signature_name: str = "serving_default",
    output_filter: tuple[str, ...] = (),
    version: int | None = None,
    version_label: str | None = None,
    use_tensor_content: bool = True,
) -> apis.PredictRequest:
    if version is not None and version_label is not None:
        raise ValueError(
            "version and version_label are a oneof upstream; choose one"
        )
    req = apis.PredictRequest()
    req.model_spec.name = model_name
    req.model_spec.signature_name = signature_name
    if version is not None:
        req.model_spec.version.value = version
    if version_label is not None:
        req.model_spec.version_label = version_label
    for key, arr in arrays.items():
        # In-place into the map entry: skips CopyFrom's second half-MB copy.
        codec.from_ndarray(arr, use_tensor_content=use_tensor_content, out=req.inputs[key])
    req.output_filter.extend(output_filter)
    return req


class ShardedPredictClient:
    """Async fan-out over a fixed backend host list.

    With one host this degenerates to a plain client (the DCNClientSimple
    role); with several it is the reference's multi-backend scatter/gather.
    """

    def __init__(
        self,
        hosts: list[str],
        model_name: str = "DCN",
        signature_name: str = "serving_default",
        output_key: str = "prediction_node",
        timeout_s: float = 10.0,
        use_tensor_content: bool = True,
        channels_per_host: int = 1,
        full_async: bool = True,
        failover_attempts: int = 0,
        version_label: str | None = None,
        channel_credentials: "grpc.ChannelCredentials | None" = None,
    ):
        if not hosts:
            raise ValueError("need at least one backend host")
        self.hosts = list(hosts)
        self.model_name = model_name
        self.signature_name = signature_name
        # Route by version label ("stable"/"canary") instead of latest —
        # the server resolves it per request, so a label retarget flips
        # this client's traffic with no reconnect.
        self.version_label = version_label
        self.output_key = output_key
        self.timeout_s = timeout_s
        self.use_tensor_content = use_tensor_content
        # full_async=True fans the per-shard RPCs out concurrently (the
        # reference's default CompletableFuture mode, DCNClient.java:27,
        # 146-159); False issues them sequentially in host order — the
        # legacy mode's *scheduling* without replicating its out-of-order
        # merge laxity (merge order stays pinned either way).
        self.full_async = full_async
        # Beyond the reference (whose async mode let a dead host kill the
        # load thread, DCNClient.java:158-159): a shard whose home backend
        # fails with a reroutable status retries on the next host(s), up
        # to this many extra attempts. Results stay keyed by SHARD index,
        # so the host-order merge semantics are untouched. 0 = reference
        # fail-fast behavior.
        self.failover_attempts = max(0, failover_attempts)
        # Long-lived plaintext channels per host, created once and shared
        # (DCNClient.java:118-125). channels_per_host > 1 stripes requests
        # over several HTTP/2 connections — one connection's flow-control
        # window throttles a half-MB-per-request load at high concurrency.
        self.channels_per_host = max(1, channels_per_host)
        opts = list(LARGE_MESSAGE_CHANNEL_OPTIONS)
        # TLS when the server runs --ssl-config-file: pass
        # grpc.ssl_channel_credentials(root_certificates=..., [+ client key/
        # cert for mTLS]); None keeps the reference's plaintext channels.
        make_channel = (
            (lambda h: grpc.aio.secure_channel(h, channel_credentials, options=opts))
            if channel_credentials is not None
            else (lambda h: grpc.aio.insecure_channel(h, options=opts))
        )
        self._channels = [
            [make_channel(h) for _ in range(self.channels_per_host)]
            for h in self.hosts
        ]
        self._stubs = [
            [PredictionServiceStub(ch) for ch in per_host] for per_host in self._channels
        ]
        self._rr = 0

    async def close(self) -> None:
        for per_host in self._channels:
            for ch in per_host:
                await ch.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def _shard_call(self, i: int, rr: int, invoke) -> np.ndarray:
        """One shard's RPC with failover: `invoke(stub)` issues the call on
        the chosen stub (message path uses stub.Predict, prepared-bytes path
        stub.PredictRaw); host rotation, reroutable-status retry, and error
        wrapping are shared here so the two paths cannot diverge."""
        for attempt in range(self.failover_attempts + 1):
            host_idx = (i + attempt) % len(self.hosts)
            stubs = self._stubs[host_idx]
            # rr advances once per logical request (not per shard), so shard
            # i of request r lands on channel (r + i) % k: consecutive
            # requests stripe every host's channels even when the shard
            # count divides k.
            try:
                resp = await invoke(stubs[(rr + i) % len(stubs)])
            except grpc.aio.AioRpcError as e:
                code_name = getattr(e.code(), "name", str(e.code()))
                if (
                    attempt < self.failover_attempts
                    and code_name in _FAILOVER_CODES
                ):
                    continue  # reroute this shard to the next host
                raise PredictClientError(
                    self.hosts[host_idx], e.code(), e.details()
                ) from e
            return codec.to_ndarray(resp.outputs[self.output_key])
        raise AssertionError("unreachable: loop always returns or raises")

    async def _predict_shard(self, i: int, shard: dict[str, np.ndarray], rr: int) -> np.ndarray:
        req = build_predict_request(
            shard,
            self.model_name,
            self.signature_name,
            output_filter=(self.output_key,),
            version_label=self.version_label,
            use_tensor_content=self.use_tensor_content,
        )
        return await self._shard_call(
            i, rr, lambda stub: stub.Predict(req, timeout=self.timeout_s)
        )

    async def _fan_out(self, shard_coros: list, sort_scores: bool) -> np.ndarray:
        """Await the per-shard coroutines (concurrently or in host order),
        host-order merge, optional ascending sort (Collections.sort parity,
        DCNClient.java:195)."""
        if len(shard_coros) == 1:
            # Degenerate fan-out: await the one RPC directly — gather()'s
            # task + future machinery costs several event-loop callbacks per
            # call for nothing (measurable on a single-core client).
            results = [await shard_coros[0]]
        elif self.full_async:
            results = await asyncio.gather(*shard_coros)
        else:
            results = []
            try:
                for c in shard_coros:
                    results.append(await c)
            except BaseException:
                # Close the not-yet-awaited tail so an early shard failure
                # never leaves "coroutine was never awaited" warnings.
                for c in shard_coros[len(results) + 1:]:
                    c.close()
                raise
        merged = merge_host_order(list(results))
        if sort_scores:
            merged = np.sort(merged)
        return merged

    async def predict(
        self, arrays: dict[str, np.ndarray], sort_scores: bool = False
    ) -> np.ndarray:
        """One logical request: shard -> concurrent RPCs -> host-order merge
        (-> ascending sort when ranking semantics are wanted)."""
        shards = shard_candidates(arrays, len(self.hosts))
        self._rr += 1
        rr = self._rr
        return await self._fan_out(
            [self._predict_shard(i, s, rr) for i, s in enumerate(shards)],
            sort_scores,
        )

    def prepare(self, arrays: dict[str, np.ndarray]) -> PreparedRequest:
        """Shard + build + serialize once; returns the reusable wire bytes
        for predict_prepared (see PreparedRequest)."""
        shards = shard_candidates(arrays, len(self.hosts))
        blobs = [
            build_predict_request(
                s,
                self.model_name,
                self.signature_name,
                output_filter=(self.output_key,),
                version_label=self.version_label,
                use_tensor_content=self.use_tensor_content,
            ).SerializeToString()
            for s in shards
        ]
        n = next(iter(arrays.values())).shape[0]
        return PreparedRequest(shard_blobs=blobs, candidates=n)

    async def _predict_shard_raw(self, i: int, blob: bytes, rr: int) -> np.ndarray:
        return await self._shard_call(
            i, rr, lambda stub: stub.PredictRaw(blob, timeout=self.timeout_s)
        )

    async def predict_prepared(
        self, prep: PreparedRequest, sort_scores: bool = False
    ) -> np.ndarray:
        """predict() over pre-serialized shard bytes: identical wire traffic
        and merge/sort semantics, none of the per-call build+serialize."""
        self._rr += 1
        rr = self._rr
        return await self._fan_out(
            [
                self._predict_shard_raw(i, b, rr)
                for i, b in enumerate(prep.shard_blobs)
            ],
            sort_scores,
        )


def client_from_config(cfg) -> ShardedPredictClient:
    """ShardedPredictClient from a utils.config.ClientConfig — every
    reference knob (DCNClient.java:25-40) lands on the matching client
    parameter, including the sync/async mode flag."""
    return ShardedPredictClient(
        list(cfg.hosts),
        model_name=cfg.model_name,
        signature_name=cfg.signature_name,
        output_key=cfg.output_key,
        timeout_s=cfg.timeout_s,
        use_tensor_content=cfg.use_tensor_content,
        full_async=cfg.full_async_mode,
        failover_attempts=cfg.failover_attempts,
        version_label=cfg.version_label or None,
        channel_credentials=_credentials_from_config(cfg),
    )


def _credentials_from_config(cfg):
    """grpc.ssl_channel_credentials from the ClientConfig tls_* file paths
    (None when ALL unset — plaintext, the reference default). Any tls_*
    key set means the operator intended TLS: a partial identity pair is a
    config error, never a silent plaintext downgrade."""
    if not (cfg.tls_root_certs_file or cfg.tls_client_cert_file
            or cfg.tls_client_key_file):
        return None
    if bool(cfg.tls_client_key_file) != bool(cfg.tls_client_cert_file):
        raise ValueError(
            "tls_client_key_file and tls_client_cert_file must be set "
            "together (the mTLS identity pair); got key="
            f"{cfg.tls_client_key_file!r} cert={cfg.tls_client_cert_file!r}"
        )

    def read(path):
        return open(path, "rb").read() if path else None

    return grpc.ssl_channel_credentials(
        root_certificates=read(cfg.tls_root_certs_file),
        private_key=read(cfg.tls_client_key_file),
        certificate_chain=read(cfg.tls_client_cert_file),
    )


def predict_sync(
    host: str,
    arrays: dict[str, np.ndarray],
    model_name: str = "DCN",
    signature_name: str = "serving_default",
    timeout_s: float = 10.0,
    version: int | None = None,
    version_label: str | None = None,
    channel_credentials: "grpc.ChannelCredentials | None" = None,
) -> dict[str, np.ndarray]:
    """Single-backend blocking Predict (the DCNClientSimple smoke role,
    DCNClientSimple.java:25-62) returning all outputs."""
    with (
        grpc.secure_channel(host, channel_credentials)
        if channel_credentials is not None
        else grpc.insecure_channel(host)
    ) as ch:
        stub = PredictionServiceStub(ch)
        req = build_predict_request(
            arrays, model_name, signature_name,
            version=version, version_label=version_label,
        )
        resp = stub.Predict(req, timeout=timeout_s)
    return {k: codec.to_ndarray(v) for k, v in resp.outputs.items()}
