"""Fan-out Predict client — the reference's split/merge path, asyncio-native.

Reproduces C2-C6/C9 of the component inventory (SURVEY.md §2.1): one
long-lived channel per backend host shared by all in-flight requests
(DCNClient.java:118-125), per-request candidate sharding (contiguous,
remainder-to-last), concurrent per-shard Predict RPCs, host-order merge of
each shard's output tensor (DCNClient.java:161-164), and optional ascending
sort of the merged scores — the ranking step (DCNClient.java:195).

Improvements over the reference kept deliberately semantic-preserving:
asyncio tasks replace the 16-thread pool + blocking stubs (asynchrony moves
into gRPC itself), per-RPC deadlines + typed errors replace
print-and-drop/thread-death failure modes (DCNClient.java:158-159,185-188),
and channels actually close (the reference's shutDownChannels never calls
shutdown(), DCNClient.java:127-135).
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import random
import time

import grpc
import grpc.aio
import numpy as np

from .. import codec, faults
from ..proto import serving_apis_pb2 as apis
from ..utils import tracing
# LARGE_MESSAGE_CHANNEL_OPTIONS re-exported: transport tuning lives with
# the grpc wiring, but callers historically reach it through the client.
from ..proto.service_grpc import (  # noqa: F401
    LARGE_MESSAGE_CHANNEL_OPTIONS,
    PredictionServiceStub,
)
from .health import HALF_OPEN, BackendScoreboard
from .partition import (
    StreamingMerger,
    affinity_groups,
    index_runs,
    merge_host_order,
    partition_bounds,
    shard_candidates,
)


class PredictClientError(RuntimeError):
    def __init__(self, host: str, code, details: str):
        super().__init__(f"Predict to {host} failed: {code} {details}")
        self.host = host
        self.code = code


def keepalive_channel_options(
    keepalive_time_ms: int = 10_000, keepalive_timeout_ms: int = 5_000
) -> tuple[tuple[str, int], ...]:
    """HTTP/2 keepalive pings for the long-lived backend channels: a
    silently-dead backend (power loss, network partition — no FIN, no RST)
    is detected within time+timeout instead of hanging every in-flight RPC
    until its full deadline. max_pings_without_data=0 +
    permit_without_calls=1 keep the probe running on an idle channel too,
    so the FIRST request after an idle period doesn't eat the discovery."""
    return (
        ("grpc.keepalive_time_ms", int(keepalive_time_ms)),
        ("grpc.keepalive_timeout_ms", int(keepalive_timeout_ms)),
        ("grpc.http2.max_pings_without_data", 0),
        ("grpc.keepalive_permit_without_calls", 1),
    )


@dataclasses.dataclass
class ResilienceCounters:
    """Client-side resilience events (bench.py / soak report these)."""

    hedges_fired: int = 0
    hedges_won: int = 0
    failovers: int = 0
    backoff_sleeps: int = 0
    partial_responses: int = 0
    # Streamed Predict (ISSUE 9): shards served over PredictStream and
    # the sub-batch chunks their incremental merges consumed.
    streamed_shards: int = 0
    stream_chunks: int = 0
    # Overload plane (serving/overload.py): RESOURCE_EXHAUSTED sheds seen
    # (the backend is busy, not dead), and backoffs that honored a
    # server-sent retry-after-ms pushback hint.
    pushbacks_received: int = 0
    retry_after_honored: int = 0
    # Retry budget (ISSUE 11): requests whose per-request attempt budget
    # (max_attempts_total across failover hops + hedges + streamed
    # reroutes) ran out — the storm-suppression the recovery plane's
    # quarantine relies on.
    retry_budget_exhausted: int = 0
    # Recovery plane (ISSUE 12 satellite): UNAVAILABLE answers that
    # carried the replica-rebuilding marker (a quarantined backend
    # announcing its own recovery cycle) — steered around as "alive but
    # rebuilding", never charged to the ejection budget.
    rebuilding_hints: int = 0
    # Drain hints (ISSUE 17 satellite): UNAVAILABLE refusals carrying the
    # GracefulShutdown drain detail, or NOT_SERVING health answers whose
    # x-dts-health-reason trailer says "draining" — the backend is
    # LEAVING. Recorded as kind="draining" on the scoreboard: steered
    # away from immediately, no ejection budget spent, and the
    # rebuilding retry window never cycled.
    draining_hints: int = 0
    # int8 score response wire (ISSUE 12): responses whose score tensor
    # arrived as DT_INT8 + sidecars and was dequantized locally.
    int8_responses: int = 0
    # Integrity plane (ISSUE 20): responses whose score tensor failed
    # the x-dts-score-crc verify — caught BEFORE the merge, recorded
    # kind="corrupt" on the scoreboard, retried on another backend.
    corrupt_responses: int = 0
    # NaN scores encountered by the ranking sort and pushed to the
    # deterministic worst-rank tail instead of floating arbitrarily
    # through the comparison order (defense in depth for unscreened
    # backends).
    nan_scores_merged: int = 0


class _AttemptBudget:
    """Per-logical-request pool of EXTRA backend attempts (beyond each
    shard's guaranteed first try): failover retries and hedges draw from
    it; when dry, the shard fails with its last error instead of
    mounting another attempt. Shared by every shard task of one request
    (asyncio single-threaded mutation — no lock needed)."""

    __slots__ = ("left", "tripped")

    def __init__(self, extra: int):
        self.left = max(int(extra), 0)
        # Exhaustion is counted ONCE per logical request, not once per
        # shard/hedge that notices the dry pool.
        self.tripped = False

    def take(self) -> bool:
        if self.left > 0:
            self.left -= 1
            return True
        return False


# Overload-plane wire metadata (serving/overload.py repeats these; the
# client package must stay importable without the serving package's jax
# dependency, so the literals live on both sides).
_CRITICALITY_KEY = "x-dts-criticality"
_RETRY_AFTER_KEY = "retry-after-ms"
# int8 score response wire opt-in (ops/autotune.py SCORE_WIRE_KEY — the
# literal lives on both sides for the same jax-free-import reason).
_SCORE_WIRE_KEY = "x-dts-score-wire"
# Substring a quarantined replica's UNAVAILABLE refusal carries
# (serving/batcher.py DeviceQuarantinedError message: "replica
# quarantined: device executor is being rebuilt ..."): the backend is
# alive and ANSWERING — it announced its own executor rebuild — so the
# scoreboard marks it rebuilding instead of burning ejection budget.
# A drain refusal ("server draining ...") deliberately does NOT match:
# a draining replica is leaving, not coming back.
_REBUILDING_MARKER = "replica quarantined"
# Substring a DRAINING replica's UNAVAILABLE refusal carries
# (serving/service.py _refuse_if_draining: "server is draining (shutdown
# in progress); retry against another backend") and the value the health
# servicer's x-dts-health-reason trailer uses. Recorded as
# kind="draining" (ISSUE 17 satellite): the scoreboard steers away from
# the FIRST hint and never cycles the rebuilding retry window — before
# this split, a draining replica burned the whole rebuilding_streak_limit
# before ejection, eating one routed request per busy-window cycle.
_DRAINING_MARKER = "server is draining"
# grpc.health.v1 carries no detail field, so the serving stack annotates
# NOT_SERVING Check answers with the refusal reason ("draining" /
# "quarantined" / "starting") in this trailing-metadata key. Advisory:
# absent on foreign servers, the bare status keeps its historical
# rebuilding interpretation.
_HEALTH_REASON_KEY = "x-dts-health-reason"
# Retry-budget forwarding across a fleet router hop (ISSUE 17): a client
# with max_attempts_total set advertises it here; the router caps its own
# server-side attempt budget at min(local, advertised) so the edge's
# storm-suppression intent survives the hop.
_RETRY_BUDGET_KEY = "x-dts-retry-budget"

# Initial-metadata key traced servers answer with so client.rpc spans can
# label the resolved peer (router vs replica) — ISSUE 18 satellite.
_PEER_ROLE_KEY = "x-dts-peer-role"

# Integrity-plane wire checksums (ISSUE 20; serving/integrity.py repeats
# these — the jax-free-import rationale again): the client stamps
# per-input CRC32C sidecars on requests, the server stamps score-tensor
# checksums on responses for opted-in clients to verify before merge.
_INPUT_CRC_KEY = codec.CRC_INPUT_MD
_SCORE_CRC_KEY = codec.CRC_SCORE_MD


def _flip_tensor_bytes(tp) -> None:
    """Deterministic wire corruption (the wire_corrupt fault site): flip
    one payload bit of a TensorProto so the CRC verify on the receiving
    end MUST catch it — the shape/dtype stay valid, only the value
    changes (the silent-corruption scenario, not a decode error)."""
    if tp.tensor_content:
        buf = bytearray(tp.tensor_content)
        buf[len(buf) // 2] ^= 0x01
        tp.tensor_content = bytes(buf)
    elif len(tp.float_val):
        tp.float_val[0] = tp.float_val[0] + 1.0


# Per-request override channel (ISSUE 17): the fleet router serves many
# edge requests through ONE embedded ShardedPredictClient, and each
# inbound RPC carries its own deadline / criticality / traceparent /
# retry budget. Client-level attributes cannot express that, so the
# router (or any embedding caller) wraps predict() in
# `with client.request_overrides(...)`: contextvars propagate into every
# shard task asyncio spawns under the call, and concurrent requests see
# only their own values. All default to None = use the client attribute.
_OVERRIDES: "contextvars.ContextVar[dict | None]" = contextvars.ContextVar(
    "dts_client_request_overrides", default=None
)


class _OverrideScope:
    __slots__ = ("_values", "_token")

    def __init__(self, values: dict):
        self._values = values
        self._token = None

    def __enter__(self):
        self._token = _OVERRIDES.set(self._values)
        return self

    def __exit__(self, *exc):
        _OVERRIDES.reset(self._token)
        return False


def _retry_after_ms_of(err) -> int | None:
    """The server's retry-after-ms pushback hint from an RPC error's
    trailing metadata (None when absent/unparseable — hints are advisory,
    a malformed one must never fail the failover path)."""
    get = getattr(err, "trailing_metadata", None)
    if get is None:
        return None
    try:
        md = get() if callable(get) else get
        for key, value in md or ():
            if key == _RETRY_AFTER_KEY:
                return max(int(value), 0)
    except Exception:  # noqa: BLE001 — advisory only
        return None
    return None


@dataclasses.dataclass
class PredictResult:
    """predict()'s return shape in partial-results mode.

    `scores` holds the merged candidates of every shard that ANSWERED, in
    host order; `missing_ranges` are the [start, end) candidate ranges of
    shards whose failover chain exhausted (empty when nothing failed);
    `degraded` flags the partial case so callers cannot mistake a reduced
    candidate set for a full ranking."""

    scores: np.ndarray
    missing_ranges: tuple[tuple[int, int], ...] = ()
    degraded: bool = False


class _StreamIncompleteError(Exception):
    """A PredictStream ended cleanly without covering the request — a
    server bug or a mid-stream connection teardown grpc surfaced as a
    normal end. Duck-types the AioRpcError surface (code()/details()) so
    the shard machinery treats it like any reroutable backend failure."""

    def __init__(self, detail: str):
        super().__init__(detail)
        self._detail = detail

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return self._detail


class _ShardAttemptError(Exception):
    """Internal: one failed shard attempt, tagged with the backend that
    failed it (the failover loop and hedge arbiter route on this)."""

    def __init__(self, host_idx: int, code, details: str,
                 retry_after_ms: int | None = None):
        super().__init__(details)
        self.host_idx = host_idx
        self.code = code  # grpc.StatusCode-like (has .name)
        self.details = details
        # Server pushback hint (overload plane): the failover backoff
        # waits at least this long before the next attempt.
        self.retry_after_ms = retry_after_ms

    @property
    def code_name(self) -> str:
        return getattr(self.code, "name", str(self.code))


@dataclasses.dataclass
class PreparedRequest:
    """A logical request pre-sharded and pre-serialized to wire bytes.

    For hot candidate sets that are re-scored continuously (the reference's
    own benchmark re-sends ONE payload for all 6,000 requests,
    DCNClient.java:208-210), building + serializing the half-MB
    PredictRequest per call is pure re-work — on a single-core client it is
    ~10% of the whole request budget (round-3 profile: 220 us of 2.4 ms).
    prepare() hoists it out of the loop; predict_prepared() sends the cached
    bytes through the raw-bytes stub. The wire bytes are identical to
    predict()'s.

    Under placement="affinity" (ISSUE 14 satellite) the blobs are the
    per-HOME row groups instead of the contiguous split: `homes[i]` is
    blob i's affine backend and `index_groups[i]` its original row
    indices, so predict_prepared scatters the merged scores back into
    candidate order exactly like predict() does. Both None = the
    contiguous split (positional shard i -> host i)."""

    shard_blobs: list[bytes]
    candidates: int
    homes: "tuple[int, ...] | None" = None
    index_groups: "tuple | None" = None


# Failures worth rerouting to another backend: the host is down/slow/
# shedding. Deterministic request errors (INVALID_ARGUMENT, NOT_FOUND)
# would fail identically everywhere and never retry.
_FAILOVER_CODES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED"})


def compact_payload(
    arrays: dict[str, np.ndarray], vocab_size: int
) -> dict[str, np.ndarray]:
    """Pre-apply the server's own first transforms client-side so the wire
    carries half the bytes: int64 ids -> folded int32 (exact mod, the
    server's host fold; models re-fold idempotently) and f32 weights ->
    bf16 (the models' compute-dtype cast, round-to-nearest-even both
    sides). Scores are bit-identical to the wide encoding — the packed
    device bytes are the same — while the 516 KB reference request becomes
    258 KB. The transport is >half the single-core request budget (~1.7
    ms/MB through grpc-python), so this is the client knob with the largest
    throughput effect; the server accepts it via the compact-wire widening
    in service._decode_and_validate."""
    import ml_dtypes

    from .. import native

    out = {}
    for k, v in arrays.items():
        if k == "feat_ids" and v.dtype == np.int64:
            # The server's own canonical fold (native one-pass when built).
            out[k] = native.fold_ids(v, vocab_size)
        elif k == "feat_wts" and v.dtype == np.float32:
            # ONLY the weights input: other float inputs (DLRM
            # dense_features) are consumed in f32 by the models and the
            # server rejects them in bf16 (service widening gate).
            out[k] = v.astype(ml_dtypes.bfloat16)
        else:
            out[k] = v
    return out


def build_predict_request(
    arrays: dict[str, np.ndarray],
    model_name: str,
    signature_name: str = "serving_default",
    output_filter: tuple[str, ...] = (),
    version: int | None = None,
    version_label: str | None = None,
    use_tensor_content: bool = True,
) -> apis.PredictRequest:
    if version is not None and version_label is not None:
        raise ValueError(
            "version and version_label are a oneof upstream; choose one"
        )
    req = apis.PredictRequest()
    req.model_spec.name = model_name
    req.model_spec.signature_name = signature_name
    if version is not None:
        req.model_spec.version.value = version
    if version_label is not None:
        req.model_spec.version_label = version_label
    for key, arr in arrays.items():
        # In-place into the map entry: skips CopyFrom's second half-MB copy.
        codec.from_ndarray(arr, use_tensor_content=use_tensor_content, out=req.inputs[key])
    req.output_filter.extend(output_filter)
    return req


class ShardedPredictClient:
    """Async fan-out over a fixed backend host list.

    With one host this degenerates to a plain client (the DCNClientSimple
    role); with several it is the reference's multi-backend scatter/gather.
    """

    def __init__(
        self,
        hosts: list[str],
        model_name: str = "DCN",
        signature_name: str = "serving_default",
        output_key: str = "prediction_node",
        timeout_s: float = 10.0,
        use_tensor_content: bool = True,
        channels_per_host: int = 1,
        full_async: bool = True,
        failover_attempts: int = 0,
        version_label: str | None = None,
        channel_credentials: "grpc.ChannelCredentials | None" = None,
        scoreboard: "BackendScoreboard | bool | None" = None,
        hedge_delay_s: float = 0.0,
        backoff_initial_s: float = 0.05,
        backoff_max_s: float = 2.0,
        partial_results: bool = False,
        health_probe: bool = False,
        keepalive_time_ms: int = 10_000,
        keepalive_timeout_ms: int = 5_000,
        score_cache=None,
        criticality: str = "",
        stream_chunk_candidates: int = 0,
        max_attempts_total: int = 0,
        score_wire_int8: bool = False,
        placement: str = "contiguous",
        integrity_checksums: bool = False,
    ):
        if not hosts:
            raise ValueError("need at least one backend host")
        if placement not in ("contiguous", "affinity"):
            raise ValueError(
                f"placement must be 'contiguous' or 'affinity', got {placement!r}"
            )
        self.hosts = list(hosts)
        self.model_name = model_name
        self.signature_name = signature_name
        # Route by version label ("stable"/"canary") instead of latest —
        # the server resolves it per request, so a label retarget flips
        # this client's traffic with no reconnect.
        self.version_label = version_label
        self.output_key = output_key
        self.timeout_s = timeout_s
        self.use_tensor_content = use_tensor_content
        # full_async=True fans the per-shard RPCs out concurrently (the
        # reference's default CompletableFuture mode, DCNClient.java:27,
        # 146-159); False issues them sequentially in host order — the
        # legacy mode's *scheduling* without replicating its out-of-order
        # merge laxity (merge order stays pinned either way).
        self.full_async = full_async
        # Beyond the reference (whose async mode let a dead host kill the
        # load thread, DCNClient.java:158-159): a shard whose home backend
        # fails with a reroutable status retries on the next host(s), up
        # to this many extra attempts. Results stay keyed by SHARD index,
        # so the host-order merge semantics are untouched. 0 = reference
        # fail-fast behavior.
        self.failover_attempts = max(0, failover_attempts)
        # --- resilience layer (client/health.py) --------------------------
        # scoreboard=True builds a default BackendScoreboard; an instance is
        # used as-is (tests inject a deterministic clock); None/False keeps
        # PR 1's blind next-host rotation.
        if scoreboard is True:
            scoreboard = BackendScoreboard(self.hosts)
        self.scoreboard: BackendScoreboard | None = scoreboard or None
        # Hedged shard RPCs: after this delay with no answer, fire a second
        # attempt on another healthy host — first answer wins, the loser is
        # cancelled. 0 = off. Tames the sick-backend tail at the cost of
        # bounded duplicate work (the hedge only exists while the primary
        # is already slower than the healthy-path p99 ought to be).
        self.hedge_delay_s = max(0.0, hedge_delay_s)
        # Jittered exponential backoff BETWEEN failover attempts: a backend
        # failing under overload (RESOURCE_EXHAUSTED) must not receive the
        # whole fleet's synchronized retry storm. Jitter is 0.5x-1.5x from
        # an ENTROPY-seeded RNG — a fixed seed would hand every client the
        # same draw sequence and re-synchronize the storm; tests that need
        # determinism set backoff_initial_s=0 or replace _jitter.
        self.backoff_initial_s = max(0.0, backoff_initial_s)
        self.backoff_max_s = max(self.backoff_initial_s, backoff_max_s)
        self._jitter = random.Random()
        # Partial-result mode: a shard whose failover chain exhausts yields
        # a DEGRADED merge (PredictResult.missing_ranges) instead of
        # failing the whole request — every shard failing still raises.
        self.partial_results = partial_results
        # Half-open ejected backends get a grpc.health.v1 Check before any
        # real traffic when enabled (needs a scoreboard to matter).
        self.health_probe = health_probe
        # Optional client-local score cache (cache/score_cache.py — the
        # SAME core the server's batcher uses, jax-free): an exact repeat
        # of a recent predict() is answered without any RPC at all. OFF by
        # default; pass a ScoreCache instance, or True for defaults.
        # Degraded (partial) merges are NEVER cached — a reduced candidate
        # set must not masquerade as the full ranking on later hits — and
        # version-label routing rides the key, so a label retarget is only
        # served stale within the cache's TTL (size it accordingly, or
        # flush on retarget).
        if score_cache is True:
            from ..cache import ScoreCache

            score_cache = ScoreCache()
        self.score_cache = score_cache or None
        # Criticality lane (overload plane): sent as x-dts-criticality
        # metadata on every RPC. "critical" / "default" / "sheddable" —
        # overloaded servers running [overload] shed sheddable traffic
        # first. "" (default) sends nothing; the server treats absent as
        # "default".
        self.criticality = str(criticality or "").strip().lower()
        # Streamed Predict (ISSUE 9): default sub-batch size hint sent as
        # x-dts-stream-chunk on predict_streamed() RPCs (0 = server
        # default). First-scores latencies are tracked per streamed shard
        # (bounded ring) — the number streaming exists to improve.
        self.stream_chunk_candidates = max(int(stream_chunk_candidates or 0), 0)
        # Retry budget (ISSUE 11 satellite): cap on TOTAL backend
        # attempts per logical request across failover hops + hedges +
        # streamed reroutes. A replica recovering from a device failure
        # answers UNAVAILABLE while quarantined; without a cap, every
        # client's failover × hedging could multiply one request into a
        # fleet-wide retry storm against the survivors. Each shard's
        # first attempt is always allowed; the budget bounds the rest.
        # 0 = unlimited (historical behavior).
        self.max_attempts_total = max(int(max_attempts_total or 0), 0)
        # Candidate placement policy (ROADMAP 4a seed, ISSUE 13
        # satellite). "contiguous" = the reference's positional split.
        # "affinity": each candidate ROW routes to the backend its
        # canonical row digest jump-hashes to (cache/digest.py row
        # identity), so a hot row always lands on the same replica's
        # warm score cache instead of re-scoring on every replica. The
        # affine backend is the group's HOME in the existing failover
        # machinery, so the scoreboard still steers a group away while
        # its home is ejected/busy/rebuilding, and results scatter back
        # into the original candidate order (bit-identical to the
        # contiguous split's merge). Covers EVERY client entry point
        # (ISSUE 14 satellite — the server's row-granular cache is what
        # the routing warms): predict() routes groups live,
        # predict_streamed() streams each group from its home (chunk
        # offsets are group-relative, so the offset-scatter merge
        # composes unchanged), and prepare()/predict_prepared() serialize
        # per-group blobs with their homes + row indices pinned on the
        # PreparedRequest.
        self.placement = placement
        # int8 score response wire (ISSUE 12): opt into DT_INT8 score
        # tensors (+ scale/min sidecar outputs, dequantized locally) via
        # x-dts-score-wire metadata — 4x fewer response bytes per score
        # against a server with [kernels] int8_score_wire on; servers
        # without the plane ignore the metadata and answer normally.
        self.score_wire_int8 = bool(score_wire_int8)
        # Integrity wire checksums (ISSUE 20): stamp x-dts-input-crc
        # CRC32C sidecars over each shard's tensor bytes (an
        # [integrity]-armed server verifies at decode and fails ONLY the
        # corrupted request), and verify the server's x-dts-score-crc
        # response stamps BEFORE the merge — a mismatch is recorded
        # kind="corrupt" on the scoreboard (steer + failover, never
        # ejection on the first hit) and the shard retries elsewhere.
        # Message-path predict() only; prepared-bytes requests skip the
        # input stamp (their bytes are frozen at prepare()) but still
        # verify responses. Servers without the plane ignore the
        # metadata and stamp nothing — both directions are advisory.
        self.integrity_checksums = bool(integrity_checksums)
        self._first_score_ms: list[float] = []
        # Per-backend rolling latency windows (ISSUE 18: the router's
        # /monitoring parity surface). None until enable_backend_windows
        # — the hot path pays one attribute read when disabled.
        self._backend_windows: dict[str, "object"] | None = None
        self.counters = ResilienceCounters()
        self._health_stubs: list[object | None] = [None] * len(self.hosts)
        # Long-lived plaintext channels per host, created once and shared
        # (DCNClient.java:118-125). channels_per_host > 1 stripes requests
        # over several HTTP/2 connections — one connection's flow-control
        # window throttles a half-MB-per-request load at high concurrency.
        self.channels_per_host = max(1, channels_per_host)
        opts = list(LARGE_MESSAGE_CHANNEL_OPTIONS)
        if keepalive_time_ms > 0:
            # keepalive_time_ms=0 opts out entirely — for channels toward
            # stock gRPC backends whose default ping-abuse policy (5-minute
            # min interval, 2 strikes) would GOAWAY a 10s pinger. The
            # in-tree servers carry KEEPALIVE_SERVER_OPTIONS and tolerate
            # these pings.
            opts += list(
                keepalive_channel_options(keepalive_time_ms, keepalive_timeout_ms)
            )
        # TLS when the server runs --ssl-config-file: pass
        # grpc.ssl_channel_credentials(root_certificates=..., [+ client key/
        # cert for mTLS]); None keeps the reference's plaintext channels.
        make_channel = (
            (lambda h: grpc.aio.secure_channel(h, channel_credentials, options=opts))
            if channel_credentials is not None
            else (lambda h: grpc.aio.insecure_channel(h, options=opts))
        )
        self._channels = [
            [make_channel(h) for _ in range(self.channels_per_host)]
            for h in self.hosts
        ]
        self._stubs = [
            [PredictionServiceStub(ch) for ch in per_host] for per_host in self._channels
        ]
        self._rr = 0

    async def close(self) -> None:
        for per_host in self._channels:
            for ch in per_host:
                await ch.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    def request_overrides(
        self,
        *,
        criticality: str | None = None,
        timeout_s: float | None = None,
        traceparent: str | None = None,
        max_attempts_total: int | None = None,
    ) -> _OverrideScope:
        """Per-request overrides for ONE predict()/predict_streamed()/
        predict_prepared() call issued inside the returned context
        (ISSUE 17: the fleet router forwards each inbound RPC's deadline,
        x-dts-criticality, traceparent, and retry budget through its
        embedded client). Contextvar-scoped: every shard/hedge task of
        the wrapped call inherits the values; concurrent requests on the
        same client see only their own. None = keep the client-level
        attribute. With tracing on, `traceparent` remote-parents the
        wrapped call's `client.predict` root (the router's embedded
        client joins the `router.route` trace, ISSUE 18); with tracing
        off it forwards verbatim on the wire, so a router hop never
        breaks the edge's trace either way."""
        return _OverrideScope({
            "criticality": criticality,
            "timeout_s": timeout_s,
            "traceparent": traceparent,
            "max_attempts_total": max_attempts_total,
        })

    @staticmethod
    def _override(key: str):
        values = _OVERRIDES.get()
        return values.get(key) if values else None

    def _rpc_timeout(self) -> float:
        """Per-attempt RPC deadline: the request override (the router
        forwarding the edge's remaining deadline) when present, else the
        client attribute."""
        t = self._override("timeout_s")
        return float(t) if t else self.timeout_s

    async def _one_rpc(
        self, i: int, rr: int, host_idx: int, invoke,
        attempt: int = 0, hedge: bool = False, extra_md: tuple = (),
    ):
        """One attempt on one backend: fault site, scoreboard recording,
        error tagging. Raises _ShardAttemptError on failure. When tracing
        is on, each attempt is its own span (hedges and failover hops
        render as siblings) and carries a W3C traceparent in the gRPC
        metadata so the server's span tree joins this trace."""
        host = self.hosts[host_idx]
        stubs = self._stubs[host_idx]
        attrs = {"host": host, "attempt": attempt}
        if hedge:
            attrs["hedge"] = True
        with tracing.start_span("client.rpc", attrs=attrs) as span:
            md = []
            if span is not None:
                md.append(
                    ("traceparent",
                     tracing.make_traceparent(span.trace_id, span.span_id))
                )
            else:
                # No local span (tracing disarmed): a forwarded
                # traceparent override still rides through verbatim, so
                # a router hop never breaks the edge's trace.
                fwd_tp = self._override("traceparent")
                if fwd_tp:
                    md.append(("traceparent", fwd_tp))
            crit = self._override("criticality")
            if crit is None:
                crit = self.criticality
            if crit:
                md.append((_CRITICALITY_KEY, crit))
            if self.max_attempts_total:
                # Advertise the retry budget across the hop (ISSUE 17):
                # a fleet router caps its own attempt budget at
                # min(local, advertised).
                md.append((_RETRY_BUDGET_KEY, str(self.max_attempts_total)))
            if self.score_wire_int8:
                md.append((_SCORE_WIRE_KEY, "int8"))
            md.extend(extra_md)
            metadata = tuple(md) or None
            t0 = time.perf_counter()
            try:
                if faults.active():
                    # Named fault site (faults.py): a rule keyed on this host
                    # can delay/fail/wedge exactly one backend of the fan-out.
                    # Bounded by the RPC timeout so an injected WEDGE presents
                    # exactly like a hung backend does on the wire: this
                    # attempt dies DEADLINE_EXCEEDED after timeout_s.
                    try:
                        await asyncio.wait_for(
                            faults.fire_async("client.rpc", key=host),
                            timeout=self._rpc_timeout(),
                        )
                    except asyncio.TimeoutError:
                        raise faults.InjectedFaultError(
                            "client.rpc", "DEADLINE_EXCEEDED",
                            f"injected wedge at {host} outlived the RPC deadline",
                        ) from None
                # rr advances once per logical request (not per shard), so shard
                # i of request r lands on channel (r + i) % k: consecutive
                # requests stripe every host's channels even when the shard
                # count divides k.
                call = invoke(stubs[(rr + i) % len(stubs)], metadata)
                resp = await call
                if span is not None:
                    # Peer-role attribution (ISSUE 18 satellite): traced
                    # servers stamp x-dts-peer-role on their INITIAL
                    # metadata, so stitched trees label each hop
                    # router/replica without guessing from ports. The
                    # streamed invoke is a plain coroutine (no call
                    # object) — getattr-guarded, advisory only.
                    get_initial = getattr(call, "initial_metadata", None)
                    if get_initial is not None:
                        try:
                            for k, v in (await get_initial()) or ():
                                if k == _PEER_ROLE_KEY and isinstance(v, str):
                                    span.attrs["peer.role"] = v
                        except Exception:  # noqa: BLE001
                            pass
            except asyncio.CancelledError:
                if self.scoreboard is not None:
                    # The attempt resolved neither way: free any half-open
                    # probe slot this host_idx holds, or a recovered backend
                    # whose probe got cancelled (caller timeout, shutdown)
                    # would be skipped by steering forever.
                    self.scoreboard.release_probe(host_idx)
                raise
            except (
                grpc.aio.AioRpcError,
                faults.InjectedFaultError,
                _StreamIncompleteError,
            ) as e:
                code = e.code()
                code_name = getattr(code, "name", str(code))
                if span is not None:
                    span.attrs["code"] = code_name
                retry_after_ms = None
                if code_name == "RESOURCE_EXHAUSTED":
                    # Overload pushback: the backend ANSWERED (alive, just
                    # shedding). Pick up its retry-after-ms hint and record
                    # "busy" — never "dead" — on the scoreboard, so a
                    # shedding backend is steered around without consuming
                    # its ejection budget (the cascade fix: ejecting it
                    # would pile its traffic onto the remaining hosts and
                    # overload them next).
                    retry_after_ms = _retry_after_ms_of(e)
                    self.counters.pushbacks_received += 1
                    if span is not None and retry_after_ms:
                        span.attrs["retry_after_ms"] = retry_after_ms
                details = e.details() or ""
                rebuilding = (
                    code_name == "UNAVAILABLE" and _REBUILDING_MARKER in details
                )
                draining = (
                    code_name == "UNAVAILABLE" and _DRAINING_MARKER in details
                )
                if draining:
                    # Drain-aware hint (ISSUE 17 satellite): the backend
                    # ANSWERED with its GracefulShutdown refusal — it is
                    # leaving, not recovering. Flip it to the scoreboard's
                    # DRAINING state: steering skips it from this first
                    # hint (no more routed requests while an alternative
                    # exists), no ejection budget is spent, and the
                    # rebuilding retry window is never cycled.
                    self.counters.draining_hints += 1
                    if span is not None:
                        span.attrs["draining"] = True
                if rebuilding:
                    # Quarantine-aware hint (ISSUE 12 satellite): the
                    # backend ANSWERED with its own recovery-cycle
                    # announcement — it is alive and will be back in
                    # seconds (MTTR ~1-4s measured). Mirror the PR-5
                    # pushback-is-not-death pattern: steer around it
                    # without consuming the consecutive-failure ejection
                    # budget (ejecting would hold traffic off for the
                    # full doubling ejection window after a sub-second
                    # rebuild, and a fleet-wide chaos event would cascade
                    # exactly like the overload case did).
                    self.counters.rebuilding_hints += 1
                    if span is not None:
                        span.attrs["rebuilding"] = True
                if self.scoreboard is not None:
                    if draining:
                        self.scoreboard.record_failure(
                            host_idx, kind="draining"
                        )
                    elif rebuilding:
                        self.scoreboard.record_failure(
                            host_idx, kind="rebuilding"
                        )
                    elif code_name == "RESOURCE_EXHAUSTED":
                        self.scoreboard.record_failure(
                            host_idx, kind="pushback",
                            retry_after_s=(
                                retry_after_ms / 1e3
                                if retry_after_ms else None
                            ),
                        )
                    elif code_name in _FAILOVER_CODES:
                        self.scoreboard.record_failure(host_idx)
                    else:
                        # A deterministic request error PROVES the backend is
                        # alive and answering — that is a health success.
                        self.scoreboard.record_success(
                            host_idx, time.perf_counter() - t0
                        )
                raise _ShardAttemptError(
                    host_idx, code, e.details(), retry_after_ms=retry_after_ms
                ) from e
            if self.integrity_checksums and hasattr(resp, "outputs"):
                # Response-direction wire integrity (ISSUE 20): verify
                # the server's score-CRC stamp BEFORE this shard's array
                # reaches the merge. Raises _ShardAttemptError
                # (UNAVAILABLE — a reroutable status) on mismatch, so
                # the failover loop retries the shard elsewhere; the
                # scoreboard takes the kind="corrupt" verdict inside.
                resp = await self._verify_response_integrity(
                    call, resp, host_idx
                )
            elapsed = time.perf_counter() - t0
            if self.scoreboard is not None:
                self.scoreboard.record_success(host_idx, elapsed)
            if self._backend_windows is not None:
                self._backend_windows[host].record(elapsed)
            return resp

    async def _verify_response_integrity(self, call, resp, host_idx: int):
        """Verify the x-dts-score-crc trailing-metadata stamp against the
        response's decoded tensor bytes. Absent stamp = server without
        the plane: advisory, pass through. Mismatch (or a payload that no
        longer decodes) = corrupt response: counted, recorded
        kind="corrupt", raised as a reroutable _ShardAttemptError."""
        # Named fault site (faults.py): response-direction wire
        # corruption — one payload bit of the score tensor flips AFTER
        # the server stamped its checksum, exactly what a bad NIC/switch
        # would do. key="response" distinguishes the direction from the
        # request-side per-input-name keys.
        if faults.active() and faults.get().has_site("wire_corrupt"):
            try:
                faults.fire("wire_corrupt", key="response")
            except faults.InjectedFaultError:
                if self.output_key in resp.outputs:
                    _flip_tensor_bytes(resp.outputs[self.output_key])
        sidecar = None
        get_trailing = getattr(call, "trailing_metadata", None)
        if get_trailing is not None:
            try:
                for k, v in (await get_trailing()) or ():
                    if k == _SCORE_CRC_KEY and isinstance(v, str):
                        sidecar = v
                        break
            except Exception:  # noqa: BLE001 — advisory metadata
                sidecar = None
        if not sidecar:
            return resp
        bad: list[str]
        try:
            stamped = codec.parse_crc_sidecar(sidecar)
            decoded = {
                name: codec.to_ndarray(resp.outputs[name])
                for name in stamped if name in resp.outputs
            }
            bad = codec.verify_crc_sidecar(decoded, sidecar)
        except codec.CodecError as e:
            # A stamped tensor that no longer decodes (or a mangled
            # sidecar) IS corruption — it must fail the verify, never
            # pass it.
            bad = [f"undecodable: {e}"]
        if bad:
            self.counters.corrupt_responses += 1
            if self.scoreboard is not None:
                self.scoreboard.record_failure(host_idx, kind="corrupt")
            raise _ShardAttemptError(
                host_idx, grpc.StatusCode.UNAVAILABLE,
                f"corrupt response: score checksum mismatch on {bad} "
                "(integrity wire verify)",
            )
        return resp

    def _hedge_target(self, used: list[int]) -> int | None:
        """Extra host for a hedged attempt: the scoreboard's best healthy
        candidate, or (scoreboard-less) the next host in rotation."""
        if self.scoreboard is not None:
            return self.scoreboard.hedge_target(exclude=tuple(used))
        n = len(self.hosts)
        for k in range(1, n):
            h = (used[0] + k) % n
            if h not in used:
                return h
        return None

    @staticmethod
    async def _first_success(pending: set):
        """First task to complete SUCCESSFULLY wins; _ShardAttemptErrors
        are tolerated while any task is still running (a primary failure
        lets the in-flight hedge finish — it is the de-facto failover).
        Returns the winning TASK; raises the first failure when every task
        failed. Cleanup (cancel + exception reaping) is the caller's."""
        first_exc: _ShardAttemptError | None = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t.cancelled():
                    continue
                exc = t.exception()
                if exc is None:
                    return t
                if isinstance(exc, _ShardAttemptError):
                    if first_exc is None:
                        first_exc = exc
                else:
                    raise exc
        raise first_exc  # every attempt failed

    async def _attempt(
        self, i: int, rr: int, host_idx: int, invoke, used: list[int],
        attempt: int = 0, budget: "_AttemptBudget | None" = None,
        extra_md: tuple = (),
    ):
        """One failover attempt, optionally hedged: the primary RPC runs on
        `host_idx`; after hedge_delay_s without an answer a second attempt
        fires on another healthy host — first ANSWER wins, the loser is
        cancelled. Hosts burned here are appended to `used` so the outer
        loop never re-tries them. A hedge is an OPTIONAL extra attempt,
        so it draws from the per-request retry budget when one is set."""
        if not self.hedge_delay_s or len(self.hosts) < 2:
            # No task wrapper: the coroutine is awaited inline, so an outer
            # cancellation (gather's sibling-cancel on another shard's
            # failure, a caller timeout) cancels the RPC itself instead of
            # orphaning a detached task.
            return await self._one_rpc(
                i, rr, host_idx, invoke, attempt=attempt, extra_md=extra_md
            )
        primary = asyncio.ensure_future(
            self._one_rpc(
                i, rr, host_idx, invoke, attempt=attempt, extra_md=extra_md
            )
        )
        tasks: dict = {primary: host_idx}
        try:
            done, _ = await asyncio.wait({primary}, timeout=self.hedge_delay_s)
            hedge = None
            if not done:
                hedge_idx = self._hedge_target(used)
                if hedge_idx is not None and (
                    budget is not None and not budget.take()
                ):
                    # Budget dry: the hedge is skipped (the primary keeps
                    # running — nothing is lost but the duplicate work).
                    self._note_budget_exhausted(budget)
                    hedge_idx = None
                if hedge_idx is not None:
                    used.append(hedge_idx)
                    self.counters.hedges_fired += 1
                    hedge = asyncio.ensure_future(
                        self._one_rpc(
                            i, rr, hedge_idx, invoke,
                            attempt=attempt, hedge=True, extra_md=extra_md,
                        )
                    )
                    tasks[hedge] = hedge_idx
            winner = await self._first_success(set(tasks))
            if winner is hedge:
                self.counters.hedges_won += 1
            return winner.result()
        finally:
            # Runs on EVERY exit — win, both-failed, outer cancellation:
            # cancel stragglers (freeing any half-open probe slot they
            # hold) and retrieve every finished task's exception so none
            # surfaces as 'Task exception was never retrieved'.
            for t, h in tasks.items():
                if not t.done():
                    t.cancel()
                    if self.scoreboard is not None:
                        self.scoreboard.release_probe(h)
            for t in tasks:
                if t.done() and not t.cancelled():
                    t.exception()
                else:
                    try:
                        await t
                    except BaseException:  # noqa: BLE001 — reaping only
                        pass

    async def _health_check(self, host_idx: int) -> str:
        """grpc.health.v1 Check on the host's first channel (overall server
        health, service \"\") — the cheap half-open probe that never costs a
        real request its latency. Returns "serving", "not_serving" (the
        server ANSWERED — alive but refusing, e.g. a recovery-cycle
        rebuild or warmup), "draining" (NOT_SERVING with the server's
        `x-dts-health-reason: draining` trailer — it is leaving, don't
        re-probe it on the rebuild cadence), "inconclusive" (no health
        service — the answer proves liveness), or "down"."""
        from ..proto import health as health_proto

        stub = self._health_stubs[host_idx]
        if stub is None:
            stub = self._health_stubs[host_idx] = health_proto.HealthStub(
                self._channels[host_idx][0]
            )
        try:
            call = stub.Check(
                health_proto.HealthCheckRequest(""),
                timeout=min(self.timeout_s, 2.0),
            )
            resp = await call
            trailing = await call.trailing_metadata()
        except grpc.aio.AioRpcError as e:
            if getattr(e.code(), "name", "") == "UNIMPLEMENTED":
                # Backend build without the health service: the answer
                # PROVES it is alive — inconclusive, so fall through to
                # the real-request probe instead of re-ejecting forever.
                return "inconclusive"
            return "down"
        except Exception:  # noqa: BLE001 — any other probe failure = down
            return "down"
        if resp.status == health_proto.SERVING:
            return "serving"
        reason = ""
        for k, v in trailing or ():
            if k == _HEALTH_REASON_KEY:
                reason = v
                break
        return "draining" if reason == "draining" else "not_serving"

    def _new_budget(self, shards: int) -> "_AttemptBudget | None":
        """Per-request attempt budget, or None when the knob is off.
        Each shard's first attempt is guaranteed (the request cannot run
        without it), so the pool holds max(max_attempts_total - shards,
        0) EXTRA attempts shared across failover hops and hedges. A
        router forwarding an edge client's x-dts-retry-budget caps the
        local knob at the advertised value via request_overrides — the
        fleet never multiplies the edge's retry intent."""
        forwarded = self._override("max_attempts_total")
        caps = [
            c for c in (self.max_attempts_total, forwarded)
            if c  # 0/None = knob off
        ]
        if not caps:
            return None
        return _AttemptBudget(min(int(c) for c in caps) - shards)

    def _note_budget_exhausted(self, budget: "_AttemptBudget") -> None:
        """Count one REQUEST's budget exhaustion (first trip only: every
        shard task and skipped hedge of the same request shares one
        budget, and the counter's contract is requests, not sites)."""
        if budget.tripped:
            return
        budget.tripped = True
        self.counters.retry_budget_exhausted += 1
        if self.scoreboard is not None:
            self.scoreboard.note_retry_budget_exhausted()

    async def _shard_call(
        self, i: int, rr: int, invoke, extract=None, budget=None,
        extra_md: tuple = (),
    ) -> np.ndarray:
        """One shard's RPC with failover: `invoke(stub, metadata)` issues
        the call on the chosen stub (message path uses stub.Predict,
        prepared-bytes path stub.PredictRaw, streamed path
        stub.PredictStream with an incremental merge inside invoke); host
        steering (scoreboard when present, blind rotation otherwise),
        hedging, jittered backoff, reroutable-status retry, and error
        wrapping are shared here so the paths cannot diverge. `extract`
        maps invoke's return value to the shard's score array (default:
        decode this client's output_key tensor from a PredictResponse —
        streamed invokes already return the merged ndarray). With tracing
        on, the shard gets a span whose children are the individual
        attempts (failover hops and hedges as siblings)."""
        with tracing.start_span("client.shard", attrs={"shard": i}):
            return await self._shard_call_impl(
                i, rr, invoke, extract, budget, extra_md
            )

    async def _shard_call_impl(
        self, i: int, rr: int, invoke, extract=None, budget=None,
        extra_md: tuple = (),
    ) -> np.ndarray:
        n = len(self.hosts)
        used: list[int] = []
        last: _ShardAttemptError | None = None
        for attempt in range(self.failover_attempts + 1):
            if attempt and budget is not None and not budget.take():
                # Per-request retry budget dry (failover hops + hedges +
                # streamed reroutes all drew from it): fail with the last
                # error instead of mounting another attempt — a
                # recovering replica must not face the whole fleet's
                # multiplied retries.
                self._note_budget_exhausted(budget)
                break
            if self.scoreboard is not None:
                host_idx = self.scoreboard.pick(i % n, exclude=tuple(used))
            else:
                host_idx = next(
                    (
                        h
                        for h in ((i + attempt + k) % n for k in range(n))
                        if h not in used
                    ),
                    None,
                )
            if host_idx is None:
                # Every host already burned (hedges count too): wrap around
                # and reuse the rotation — the pre-scoreboard failover
                # retried the same host (transient errors DO clear on
                # retry-with-backoff), and the attempt budget still bounds
                # total work. Both fallbacks always yield a host.
                host_idx = (
                    self.scoreboard.pick((i + attempt) % n)
                    if self.scoreboard is not None
                    else (i + attempt) % n
                )
            used.append(host_idx)
            try:
                # From here to the RPC the attempt may be CANCELLED (caller
                # timeout, a sibling shard's failure cancelling the gather)
                # while this host_idx holds a half-open probe slot pick()
                # just granted — the except below releases it, or the
                # backend would be steered around forever (_one_rpc covers
                # only its own await).
                if attempt:
                    # Exponential with 0.5x-1.5x jitter: retries decorrelate
                    # across clients instead of synchronizing into a storm.
                    sleep_s = 0.0
                    if self.backoff_initial_s:
                        base = min(
                            self.backoff_initial_s * (2 ** (attempt - 1)),
                            self.backoff_max_s,
                        )
                        sleep_s = base * (0.5 + self._jitter.random())
                    hint_ms = getattr(last, "retry_after_ms", None)
                    if hint_ms:
                        # Server pushback (overload plane): wait AT LEAST
                        # the retry-after-ms hint — the server sized it
                        # from its backlog's drain time, which it knows
                        # and this client can only guess. Capped by the
                        # operator's backoff ceiling; honored even with
                        # backoff disabled (the hint is the whole point
                        # of pushback).
                        sleep_s = max(
                            sleep_s, min(hint_ms / 1e3, self.backoff_max_s)
                        )
                        self.counters.retry_after_honored += 1
                    if sleep_s > 0:
                        self.counters.backoff_sleeps += 1
                        await asyncio.sleep(sleep_s)
                if (
                    self.health_probe
                    and self.scoreboard is not None
                    and self.scoreboard.state(host_idx) == HALF_OPEN
                ):
                    status = await self._health_check(host_idx)
                    if status == "draining":
                        # The server answered NOT_SERVING and NAMED the
                        # reason: GracefulShutdown drain. Flip straight to
                        # the DRAINING scoreboard state — steer away now,
                        # never cycle the rebuilding retry window on a
                        # replica that is leaving (ISSUE 17 satellite).
                        self.counters.draining_hints += 1
                        self.scoreboard.record_failure(
                            host_idx, kind="draining"
                        )
                        if last is None:
                            last = _ShardAttemptError(
                                host_idx,
                                grpc.StatusCode.UNAVAILABLE,
                                "health probe reported draining",
                            )
                        continue
                    if status == "not_serving":
                        # The server ANSWERED NOT_SERVING: alive but
                        # refusing — a recovery-cycle rebuild (or warmup).
                        # Mark it rebuilding (steer-around bias) instead
                        # of a probe FAILURE, whose doubled re-ejection
                        # would hold traffic off long after the ~seconds
                        # rebuild finished (ISSUE 12 satellite).
                        self.counters.rebuilding_hints += 1
                        self.scoreboard.record_failure(
                            host_idx, kind="rebuilding"
                        )
                        if last is None:
                            last = _ShardAttemptError(
                                host_idx,
                                grpc.StatusCode.UNAVAILABLE,
                                "health probe reported not serving",
                            )
                        continue
                    if status == "down":
                        # Probe says still down: re-eject (doubled interval)
                        # without burning a real RPC + timeout on it.
                        self.scoreboard.record_failure(host_idx)
                        if last is None:
                            last = _ShardAttemptError(
                                host_idx,
                                grpc.StatusCode.UNAVAILABLE,
                                "health probe did not answer",
                            )
                        continue
                resp = await self._attempt(
                    i, rr, host_idx, invoke, used, attempt=attempt,
                    budget=budget, extra_md=extra_md,
                )
            except asyncio.CancelledError:
                if self.scoreboard is not None:
                    self.scoreboard.release_probe(host_idx)
                raise
            except _ShardAttemptError as e:
                last = e
                if attempt < self.failover_attempts and e.code_name in _FAILOVER_CODES:
                    self.counters.failovers += 1
                    continue  # reroute this shard to the next host
                raise PredictClientError(
                    self.hosts[e.host_idx], e.code, e.details
                ) from e
            if extract is not None:
                return extract(resp)
            if self.score_wire_int8:
                tp = resp.outputs[self.output_key]
                if tp.dtype == codec.DataType.DT_INT8:
                    self.counters.int8_responses += 1
                return codec.dequantize_response_output(
                    resp.outputs, self.output_key
                )
            return codec.to_ndarray(resp.outputs[self.output_key])
        assert last is not None, "exhaustion implies at least one failure"
        raise PredictClientError(
            self.hosts[last.host_idx], last.code, last.details
        ) from last

    def enable_backend_windows(self, window_s: float = 60.0) -> None:
        """Arm per-backend rolling latency windows: every successful RPC
        records into its host's WindowedLatency (the fleet router turns
        this on so its /monitoring can show per-replica latency AS
        STEERED — hedges and failovers land on the host that answered)."""
        from ..utils.metrics import WindowedLatency

        self._backend_windows = {
            h: WindowedLatency(window_s=window_s) for h in self.hosts
        }

    def backend_window_snapshots(self) -> dict:
        """Per-backend window snapshots ({} until enabled)."""
        if self._backend_windows is None:
            return {}
        return {h: w.snapshot() for h, w in self._backend_windows.items()}

    def resilience_counters(self) -> dict:
        """Client-side resilience events + per-backend scoreboard state —
        the block bench.py and tools/soak.py report."""
        out = dataclasses.asdict(self.counters)
        if self.scoreboard is not None:
            out["scoreboard"] = self.scoreboard.snapshot()
        return out

    def resilience_prometheus_text(self) -> str:
        """resilience_counters() as Prometheus text exposition (the client
        has no scrape port; harnesses write this next to their artifacts
        so fleet dashboards ingest hedging/failover/ejection state in the
        same format as the server plane)."""
        from ..utils.metrics import resilience_prometheus_text

        return resilience_prometheus_text(self.resilience_counters())

    async def _predict_shard(
        self, i: int, shard: dict[str, np.ndarray], rr: int, budget=None
    ) -> np.ndarray:
        req = build_predict_request(
            shard,
            self.model_name,
            self.signature_name,
            output_filter=(self.output_key,),
            version_label=self.version_label,
            use_tensor_content=self.use_tensor_content,
        )
        extra_md: tuple = ()
        if self.integrity_checksums:
            # Stamp the CRC32C sidecar over the shard's TRUE tensor
            # bytes first; the fault site below then corrupts the
            # encoded proto AFTER stamping — exactly the wire-flip
            # ordering the server-side verify exists to catch. key is
            # the input tensor name, so a rule can corrupt one input of
            # a multi-tensor request.
            extra_md = ((_INPUT_CRC_KEY, codec.crc_sidecar(shard)),)
            if faults.active() and faults.get().has_site("wire_corrupt"):
                for name in list(req.inputs):
                    try:
                        faults.fire("wire_corrupt", key=name)
                    except faults.InjectedFaultError:
                        _flip_tensor_bytes(req.inputs[name])
        return await self._shard_call(
            i, rr,
            lambda stub, metadata=None: stub.Predict(
                req, timeout=self._rpc_timeout(), metadata=metadata
            ),
            budget=budget,
            extra_md=extra_md,
        )

    async def _fan_out(
        self,
        shard_coros: list,
        sort_scores: bool,
        bounds: list[tuple[int, int]] | None = None,
    ) -> "np.ndarray | PredictResult":
        """Await the per-shard coroutines (concurrently or in host order),
        host-order merge, optional ascending sort (Collections.sort parity,
        DCNClient.java:195). In partial-results mode (`bounds` carries the
        per-shard candidate ranges) shards whose failover chain exhausted
        degrade the merge instead of failing it."""
        if bounds is not None:
            return await self._fan_out_partial(shard_coros, sort_scores, bounds)
        if len(shard_coros) == 1:
            # Degenerate fan-out: await the one RPC directly — gather()'s
            # task + future machinery costs several event-loop callbacks per
            # call for nothing (measurable on a single-core client).
            results = [await shard_coros[0]]
        elif self.full_async:
            results = await asyncio.gather(*shard_coros)
        else:
            results = []
            try:
                for c in shard_coros:
                    results.append(await c)
            except BaseException:
                # Close the not-yet-awaited tail so an early shard failure
                # never leaves "coroutine was never awaited" warnings.
                for c in shard_coros[len(results) + 1:]:
                    c.close()
                raise
        return self._merge(list(results), sort_scores)

    def _merge(self, results: list, sort_scores: bool, degraded: bool = False):
        """ONE merge+optional-sort implementation (traced as client.merge)
        for the full and partial fan-out paths."""
        attrs = {"degraded": True} if degraded else None
        with tracing.start_span("client.merge", attrs=attrs):
            merged = merge_host_order(results)
            if sort_scores:
                merged = self._rank_sort(merged)
        return merged

    def _rank_sort(self, merged: np.ndarray) -> np.ndarray:
        """Ranking sort with NaN pinned deterministically to the WORST
        end (ISSUE 20 satellite). np.sort puts NaN LAST in ascending
        order — the best-rank position under the Collections.sort-parity
        read (best scores at the end) — so an unscreened backend's NaN
        would silently outrank every real score. Real scores sort
        ascending as before (bit-identical when no NaN is present); NaNs
        land at the head, counted in nan_scores_merged."""
        if merged.dtype.kind == "f":
            nan = np.isnan(merged)
            if nan.any():
                k = int(nan.sum())
                self.counters.nan_scores_merged += k
                return np.concatenate([
                    np.full(k, np.nan, merged.dtype),
                    np.sort(merged[~nan]),
                ])
        return np.sort(merged)

    @staticmethod
    def _screen_shard_failures(results: list) -> list[int]:
        """Shared failure bookkeeping for the degraded-merge fan-outs
        (contiguous partial + affinity): re-raise anything that is not a
        per-shard RPC failure (a client bug or a cancellation must never
        be laundered into a degraded merge), raise the first error when
        EVERY shard failed (an empty result would read as 'zero
        candidates scored well'), and return the failed indices."""
        for r in results:
            if isinstance(r, BaseException) and not isinstance(r, PredictClientError):
                raise r
        failed = [k for k, r in enumerate(results) if isinstance(r, BaseException)]
        if failed and len(failed) == len(results):
            raise results[0]  # total outage: degraded mode has nothing to merge
        return failed

    def _note_degraded_merge(self, missing_ranges) -> None:
        """Shared degraded-merge accounting: the partial-response counter
        plus the root-span annotation (degraded merges are tail-kept by
        the recorder, so /tracez shows WHICH candidate ranges went
        missing)."""
        self.counters.partial_responses += 1
        root = tracing.current_span()
        if root is not None:
            root.attrs["degraded"] = True
            root.annotate(
                "degraded_merge",
                missing_ranges=[list(r) for r in missing_ranges],
            )

    async def _fan_out_partial(
        self, shard_coros: list, sort_scores: bool, bounds: list[tuple[int, int]]
    ) -> PredictResult:
        """Degraded-merge fan-out: failed shards become missing_ranges.
        Shards are awaited concurrently regardless of full_async — the
        sequential mode's early-abort semantics make no sense when failures
        are survivable."""
        results = await asyncio.gather(*shard_coros, return_exceptions=True)
        failed = self._screen_shard_failures(results)
        if not failed:
            return PredictResult(scores=self._merge(list(results), sort_scores))
        missing = tuple(bounds[k] for k in failed)
        self._note_degraded_merge(missing)
        merged = self._merge(
            [r for r in results if not isinstance(r, BaseException)],
            sort_scores, degraded=True,
        )
        return PredictResult(
            scores=merged, missing_ranges=missing, degraded=True,
        )

    def _cache_key(self, arrays: dict[str, np.ndarray], sort_scores: bool) -> tuple:
        """Client cache key: model + label route + (signature, output key,
        sort flag) + the same canonical feature digest the server cache
        uses. The client never knows the resolved version number, so the
        label (or "latest") is the version axis — the TTL bounds staleness
        across retargets. The sort flag is part of the output contract
        (the cached vector is stored exactly as it was returned)."""
        return self.score_cache.make_key(
            self.model_name,
            self.version_label or "latest",
            (self.signature_name, self.output_key, bool(sort_scores)),
            arrays,
        )

    def _cache_serve(self, scores: np.ndarray):
        """Shape a cached merged-score vector like a fresh predict()'s
        return: copied (callers own their result arrays), wrapped in a
        PredictResult when partial mode is on."""
        out = scores.copy()
        if self.partial_results:
            return PredictResult(scores=out)
        return out

    async def predict(
        self, arrays: dict[str, np.ndarray], sort_scores: bool = False
    ) -> "np.ndarray | PredictResult":
        """One logical request: shard -> concurrent RPCs -> host-order merge
        (-> ascending sort when ranking semantics are wanted). Returns a
        PredictResult (possibly degraded) when partial_results is on, the
        plain merged score vector otherwise. With tracing on, this is the
        ROOT span of the distributed trace — every shard RPC (and the
        server work it lands on) joins it via the injected traceparent.
        With a client score cache armed, an exact repeat of a recent
        request returns its merged scores with no RPC at all."""
        cache_key = None
        if self.score_cache is not None:
            cache_key = self._cache_key(arrays, sort_scores)
            hit = self.score_cache.lookup(cache_key)
            if hit is not None:
                return self._cache_serve(hit["scores"])
        result = await self._predict_uncached(arrays, sort_scores)
        if cache_key is not None:
            merged = result.scores if isinstance(result, PredictResult) else result
            degraded = isinstance(result, PredictResult) and result.degraded
            if not degraded:
                # NEVER fill from a degraded merge: a reduced candidate set
                # must not be served as the full ranking to later repeats.
                self.score_cache.fill(cache_key, {"scores": merged})
        return result

    async def _predict_uncached(
        self, arrays: dict[str, np.ndarray], sort_scores: bool
    ) -> "np.ndarray | PredictResult":
        if self.placement == "affinity" and len(self.hosts) > 1:
            return await self._predict_affinity(arrays, sort_scores)
        shards = shard_candidates(arrays, len(self.hosts))
        self._rr += 1
        rr = self._rr
        n = next(iter(arrays.values())).shape[0]
        bounds = (
            partition_bounds(n, len(shards)) if self.partial_results else None
        )
        with tracing.start_root(
            "client.predict",
            traceparent=self._override("traceparent"),
            attrs={"model": self.model_name, "candidates": n,
                   "shards": len(shards)},
        ):
            budget = self._new_budget(len(shards))
            return await self._fan_out(
                [
                    self._predict_shard(i, s, rr, budget)
                    for i, s in enumerate(shards)
                ],
                sort_scores,
                bounds=bounds,
            )

    async def _predict_affinity(
        self, arrays: dict[str, np.ndarray], sort_scores: bool
    ) -> "np.ndarray | PredictResult":
        """Key-affinity fan-out (placement="affinity"): rows grouped by
        the jump hash of their canonical row digest, each group sent to
        its affine backend as that group's HOME — the existing
        steering/failover machinery then applies unchanged (the
        scoreboard routes a group elsewhere while its home is ejected/
        busy/rebuilding; hedges/retry budget/backoff all compose).
        Results scatter back by original row index, so the merged vector
        is bit-identical to the contiguous split's. Groups are always
        awaited concurrently (the partial-merge precedent: sequential
        host-order issue has no meaning for content-addressed groups).

        In partial-results mode a group whose failover chain exhausts
        degrades the merge: the surviving rows come back in candidate
        order and the lost group's rows become missing_ranges (scattered
        rows encode as several small [start, end) runs)."""
        groups = affinity_groups(arrays, len(self.hosts))
        self._rr += 1
        rr = self._rr
        n = next(iter(arrays.values())).shape[0]
        with tracing.start_root(
            "client.predict",
            traceparent=self._override("traceparent"),
            attrs={"model": self.model_name, "candidates": n,
                   "shards": len(groups), "placement": "affinity"},
        ):
            budget = self._new_budget(len(groups))
            return await self._affinity_gather(
                [idx for _h, idx, _s in groups],
                [
                    self._predict_shard(host, sub, rr, budget)
                    for host, _idx, sub in groups
                ],
                n, sort_scores,
            )

    async def _affinity_gather(
        self, index_groups: list, coros: list, n: int, sort_scores: bool
    ) -> "np.ndarray | PredictResult":
        """ONE gather+scatter implementation for every affinity entry
        point (predict / predict_streamed / predict_prepared): await the
        per-group coroutines concurrently, scatter each group's scores
        back by its original row indices (bit-identical to the
        contiguous split's merge), and in partial-results mode degrade a
        lost group into scattered missing_ranges runs."""
        results = await asyncio.gather(*coros, return_exceptions=True)
        if not self.partial_results:
            for r in results:
                if isinstance(r, BaseException):
                    raise r
        failed = set(self._screen_shard_failures(results))
        ok = [
            (index_groups[k], np.asarray(results[k]))
            for k in range(len(results)) if k not in failed
        ]
        with tracing.start_span(
            "client.merge",
            attrs={"degraded": True} if failed else None,
        ):
            idx = np.concatenate([i for i, _v in ok])
            vals = np.concatenate([v for _i, v in ok])
            if failed:
                # Surviving rows in candidate order (the degraded-
                # merge contract: a shorter vector + missing_ranges).
                merged = vals[np.argsort(idx, kind="stable")]
            else:
                merged = np.empty((n,) + vals.shape[1:], vals.dtype)
                merged[idx] = vals
            if sort_scores:
                merged = self._rank_sort(merged)
        if not failed:
            if self.partial_results:
                return PredictResult(scores=merged)
            return merged
        missing = index_runs(
            np.concatenate([index_groups[k] for k in sorted(failed)])
        )
        self._note_degraded_merge(missing)
        return PredictResult(
            scores=merged, missing_ranges=missing, degraded=True,
        )

    # ------------------------------------------------- streamed Predict

    def _note_first_scores(self, ms: float) -> None:
        self._first_score_ms.append(ms)
        if len(self._first_score_ms) > 1024:  # bounded ring
            del self._first_score_ms[:512]

    def stream_stats(self) -> dict:
        """Streamed-Predict telemetry: shards/chunks consumed and the
        first-scores latency distribution — the number streaming exists
        to improve (first scores land when the FIRST sub-batch's readback
        finishes, decoupled from the slowest)."""
        lat = np.asarray(self._first_score_ms, np.float64)
        return {
            "streamed_shards": self.counters.streamed_shards,
            "stream_chunks": self.counters.stream_chunks,
            "first_score_samples": int(lat.size),
            "first_score_p50_ms": (
                round(float(np.percentile(lat, 50)), 3) if lat.size else None
            ),
            "first_score_p99_ms": (
                round(float(np.percentile(lat, 99)), 3) if lat.size else None
            ),
        }

    async def _predict_shard_stream(
        self, i: int, shard: dict[str, np.ndarray], rr: int,
        chunk: int | None, budget=None,
    ) -> np.ndarray:
        req = build_predict_request(
            shard,
            self.model_name,
            self.signature_name,
            output_filter=(self.output_key,),
            version_label=self.version_label,
            use_tensor_content=self.use_tensor_content,
        )
        n = next(iter(shard.values())).shape[0]
        chunk_n = (
            int(chunk) if chunk is not None else self.stream_chunk_candidates
        )

        async def invoke(stub, metadata=None):
            md = tuple(metadata or ())
            if chunk_n:
                md += (("x-dts-stream-chunk", str(chunk_n)),)
            merger = StreamingMerger(n)
            t0 = time.perf_counter()
            call = stub.PredictStream(
                req, timeout=self._rpc_timeout(), metadata=md or None
            )
            first_ms: float | None = None
            async for ch in call:
                merger.add(
                    ch.offset, codec.to_ndarray(ch.outputs[self.output_key])
                )
                if first_ms is None:
                    first_ms = (time.perf_counter() - t0) * 1e3
            if not merger.complete:
                # A clean end without full coverage: reroutable — the
                # failover/hedge machinery treats it like a dead backend.
                raise _StreamIncompleteError(
                    f"stream covered {merger.filled}/{n} candidates "
                    f"(missing {merger.missing_ranges()})"
                )
            # Telemetry commits only on a COMPLETE stream: a failed or
            # hedged-and-cancelled attempt must not pollute the headline
            # first-scores distribution or the chunk counters with work
            # whose merger was discarded.
            self.counters.streamed_shards += 1
            self.counters.stream_chunks += merger.chunks
            if first_ms is not None:
                self._note_first_scores(first_ms)
            return merger.result()

        return await self._shard_call(
            i, rr, invoke, extract=lambda r: r, budget=budget
        )

    async def predict_streamed(
        self, arrays: dict[str, np.ndarray], sort_scores: bool = False,
        chunk: int | None = None,
    ) -> "np.ndarray | PredictResult":
        """predict() over the server-streaming RPC: each shard rides
        PredictStream, merging sub-batch chunks incrementally as their
        readbacks complete server-side (chunks arrive out of order; the
        merge scatters by offset). Identical result semantics to
        predict() — same host-order merge, optional sort, and (in
        partial-results mode) degraded merges with missing_ranges when a
        shard's failover chain exhausts. `chunk` overrides the
        per-sub-batch candidate count (None = this client's
        stream_chunk_candidates, 0 = the server's configured default).
        First-scores latency per shard lands in stream_stats().

        Under placement="affinity" each row GROUP streams from its home
        backend (ISSUE 14 satellite — the warm-cache routing covers the
        streamed path too): chunk offsets are relative to the group's own
        request, so the per-shard offset-scatter merge composes
        unchanged, and the merged groups scatter back into candidate
        order exactly like predict()."""
        self._rr += 1
        rr = self._rr
        n = next(iter(arrays.values())).shape[0]
        if self.placement == "affinity" and len(self.hosts) > 1:
            groups = affinity_groups(arrays, len(self.hosts))
            with tracing.start_root(
                "client.predict",
                traceparent=self._override("traceparent"),
                attrs={"model": self.model_name, "candidates": n,
                       "shards": len(groups), "streamed": True,
                       "placement": "affinity"},
            ):
                budget = self._new_budget(len(groups))
                return await self._affinity_gather(
                    [idx for _h, idx, _s in groups],
                    [
                        self._predict_shard_stream(host, sub, rr, chunk, budget)
                        for host, _idx, sub in groups
                    ],
                    n, sort_scores,
                )
        shards = shard_candidates(arrays, len(self.hosts))
        bounds = (
            partition_bounds(n, len(shards)) if self.partial_results else None
        )
        with tracing.start_root(
            "client.predict",
            traceparent=self._override("traceparent"),
            attrs={"model": self.model_name, "candidates": n,
                   "shards": len(shards), "streamed": True},
        ):
            budget = self._new_budget(len(shards))
            return await self._fan_out(
                [
                    self._predict_shard_stream(i, s, rr, chunk, budget)
                    for i, s in enumerate(shards)
                ],
                sort_scores,
                bounds=bounds,
            )

    def prepare(self, arrays: dict[str, np.ndarray]) -> PreparedRequest:
        """Shard + build + serialize once; returns the reusable wire bytes
        for predict_prepared (see PreparedRequest). Under
        placement="affinity" the split is the per-home row grouping
        (ISSUE 14 satellite): each blob carries one backend's affine rows
        with its home + original row indices pinned on the result, so the
        prepared-bytes path routes rows to warm caches too."""

        def _blob(s: dict) -> bytes:
            return build_predict_request(
                s,
                self.model_name,
                self.signature_name,
                output_filter=(self.output_key,),
                version_label=self.version_label,
                use_tensor_content=self.use_tensor_content,
            ).SerializeToString()

        n = next(iter(arrays.values())).shape[0]
        if self.placement == "affinity" and len(self.hosts) > 1:
            groups = affinity_groups(arrays, len(self.hosts))
            return PreparedRequest(
                shard_blobs=[_blob(sub) for _h, _idx, sub in groups],
                candidates=n,
                homes=tuple(h for h, _idx, _s in groups),
                index_groups=tuple(idx for _h, idx, _s in groups),
            )
        shards = shard_candidates(arrays, len(self.hosts))
        return PreparedRequest(shard_blobs=[_blob(s) for s in shards], candidates=n)

    async def _predict_shard_raw(
        self, i: int, blob: bytes, rr: int, budget=None
    ) -> np.ndarray:
        return await self._shard_call(
            i, rr,
            lambda stub, metadata=None: stub.PredictRaw(
                blob, timeout=self._rpc_timeout(), metadata=metadata
            ),
            budget=budget,
        )

    async def predict_prepared(
        self, prep: PreparedRequest, sort_scores: bool = False
    ) -> "np.ndarray | PredictResult":
        """predict() over pre-serialized shard bytes: identical wire traffic
        and merge/sort semantics (including partial-results degradation),
        none of the per-call build+serialize. An affinity-prepared request
        (prepare() under placement="affinity") sends each blob to its
        pinned home backend and scatters the scores back by the pinned row
        indices — the warm-cache routing covers the prepared path too."""
        self._rr += 1
        rr = self._rr
        if prep.homes is not None:
            with tracing.start_root(
                "client.predict",
                traceparent=self._override("traceparent"),
                attrs={"model": self.model_name,
                       "candidates": prep.candidates,
                       "shards": len(prep.shard_blobs), "prepared": True,
                       "placement": "affinity"},
            ):
                budget = self._new_budget(len(prep.shard_blobs))
                return await self._affinity_gather(
                    list(prep.index_groups),
                    [
                        self._predict_shard_raw(home, b, rr, budget)
                        for home, b in zip(prep.homes, prep.shard_blobs)
                    ],
                    prep.candidates, sort_scores,
                )
        bounds = (
            partition_bounds(prep.candidates, len(prep.shard_blobs))
            if self.partial_results
            else None
        )
        with tracing.start_root(
            "client.predict",
            traceparent=self._override("traceparent"),
            attrs={"model": self.model_name, "candidates": prep.candidates,
                   "shards": len(prep.shard_blobs), "prepared": True},
        ):
            budget = self._new_budget(len(prep.shard_blobs))
            return await self._fan_out(
                [
                    self._predict_shard_raw(i, b, rr, budget)
                    for i, b in enumerate(prep.shard_blobs)
                ],
                sort_scores,
                bounds=bounds,
            )


def client_from_config(cfg) -> ShardedPredictClient:
    """ShardedPredictClient from a utils.config.ClientConfig — every
    reference knob (DCNClient.java:25-40) lands on the matching client
    parameter, including the sync/async mode flag."""
    from .health import ScoreboardConfig

    scoreboard = (
        BackendScoreboard(
            list(cfg.hosts),
            ScoreboardConfig(
                failure_threshold=cfg.ejection_failures,
                ejection_s=cfg.ejection_interval_s,
            ),
        )
        if cfg.health_scoreboard
        else None
    )
    return ShardedPredictClient(
        list(cfg.hosts),
        model_name=cfg.model_name,
        signature_name=cfg.signature_name,
        output_key=cfg.output_key,
        timeout_s=cfg.timeout_s,
        use_tensor_content=cfg.use_tensor_content,
        full_async=cfg.full_async_mode,
        failover_attempts=cfg.failover_attempts,
        version_label=cfg.version_label or None,
        channel_credentials=_credentials_from_config(cfg),
        scoreboard=scoreboard,
        hedge_delay_s=cfg.hedge_delay_ms / 1e3,
        backoff_initial_s=cfg.backoff_initial_ms / 1e3,
        backoff_max_s=cfg.backoff_max_ms / 1e3,
        partial_results=cfg.partial_results,
        health_probe=cfg.health_probe,
        keepalive_time_ms=cfg.keepalive_time_ms,
        keepalive_timeout_ms=cfg.keepalive_timeout_ms,
        criticality=cfg.criticality,
        max_attempts_total=cfg.max_attempts_total,
        placement=cfg.placement,
        integrity_checksums=getattr(cfg, "integrity_checksums", False),
    )


def _credentials_from_config(cfg):
    """grpc.ssl_channel_credentials from the ClientConfig tls_* file paths
    (None when ALL unset — plaintext, the reference default). Any tls_*
    key set means the operator intended TLS: a partial identity pair is a
    config error, never a silent plaintext downgrade."""
    if not (cfg.tls_root_certs_file or cfg.tls_client_cert_file
            or cfg.tls_client_key_file):
        return None
    if bool(cfg.tls_client_key_file) != bool(cfg.tls_client_cert_file):
        raise ValueError(
            "tls_client_key_file and tls_client_cert_file must be set "
            "together (the mTLS identity pair); got key="
            f"{cfg.tls_client_key_file!r} cert={cfg.tls_client_cert_file!r}"
        )

    def read(path):
        return open(path, "rb").read() if path else None

    return grpc.ssl_channel_credentials(
        root_certificates=read(cfg.tls_root_certs_file),
        private_key=read(cfg.tls_client_key_file),
        certificate_chain=read(cfg.tls_client_cert_file),
    )


# Per-row stage provenance output a cascade-armed server appends to the
# response (serving/cascade.py): 1 = the row was pruned after stage 1 and
# carries its stage-1 score; 2 = the row survived and carries the full
# model's score. Rides the response like the int8-wire sidecars — an
# extra tensor beyond the signature, absent when the cascade is off.
CASCADE_STAGE_KEY = "cascade_stage"


def cascade_stage(response) -> "np.ndarray | None":
    """Per-row cascade provenance from a Predict response — accepts the
    raw PredictResponse proto or a decoded outputs dict (predict_sync's
    return). None when the server ran no cascade for this request.

    Note the fleet router tier merges SCORES across replica shards and
    re-encodes, so provenance survives only on direct replica responses.
    """
    outputs = getattr(response, "outputs", response)
    if CASCADE_STAGE_KEY not in outputs:
        return None
    value = outputs[CASCADE_STAGE_KEY]
    if isinstance(value, np.ndarray):
        return value
    return codec.to_ndarray(value)


def predict_sync(
    host: str,
    arrays: dict[str, np.ndarray],
    model_name: str = "DCN",
    signature_name: str = "serving_default",
    timeout_s: float = 10.0,
    version: int | None = None,
    version_label: str | None = None,
    channel_credentials: "grpc.ChannelCredentials | None" = None,
) -> dict[str, np.ndarray]:
    """Single-backend blocking Predict (the DCNClientSimple smoke role,
    DCNClientSimple.java:25-62) returning all outputs."""
    with (
        grpc.secure_channel(host, channel_credentials)
        if channel_credentials is not None
        else grpc.insecure_channel(host)
    ) as ch:
        stub = PredictionServiceStub(ch)
        req = build_predict_request(
            arrays, model_name, signature_name,
            version=version, version_label=version_label,
        )
        resp = stub.Predict(req, timeout=timeout_s)
    return {k: codec.to_ndarray(v) for k, v in resp.outputs.items()}


# ------------------------------------------------------- label feedback


def label_keys(arrays: dict[str, np.ndarray]) -> list[str]:
    """Per-candidate join keys for the server's label-feedback plane
    (serving/quality.py): a hex digest of each row's canonical feature
    bytes, computed over the EXACT arrays this client sends — the server
    computes the same digest over the arrays it decodes, so the keys meet
    in the middle with no id plumbing through the Predict protocol.
    Compute over the same encoding you send (a compact_payload request
    needs keys over the compact arrays)."""
    from ..cache.digest import row_label_keys

    return row_label_keys(arrays)


def report_label(
    rest_base_url: str,
    key: str | list[str],
    label: float | list[float],
    ts: float | None = None,
    timeout_s: float = 5.0,
) -> dict:
    """Report outcome labels to a server's label-feedback plane
    (POST /labelz on the REST surface, serving/quality.py): the
    client-side half of the windowed-AUC/calibration loop. `key` is a
    per-row digest from label_keys() (or a trace id, optionally
    `#<row>`); a key and its BINARY label (0/1 — the AUC ranks exact
    class membership) pair positionally when lists are given. `ts` is
    the label EVENT's epoch time, feeding the server's feedback-delay
    telemetry (never window membership). Returns the server's
    {"joined": n, "orphaned": m} — an orphaned label means the server
    no longer holds (or never sampled) that key's score. Blocking,
    stdlib-only (urllib): label feedback is an offline/batch path, not
    the serving hot path."""
    import json as json_mod
    import urllib.request

    keys = key if isinstance(key, (list, tuple)) else [key]
    labels = label if isinstance(label, (list, tuple)) else [label]
    if len(keys) != len(labels):
        raise ValueError(
            f"{len(keys)} keys vs {len(labels)} labels — they pair positionally"
        )
    items = [
        {"id": str(k), "label": float(lb),
         **({"ts": float(ts)} if ts is not None else {})}
        for k, lb in zip(keys, labels)
    ]
    req = urllib.request.Request(
        rest_base_url.rstrip("/") + "/labelz",
        data=json_mod.dumps({"labels": items}).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json_mod.loads(resp.read().decode("utf-8"))
